"""North-star benchmark: GBM trees/sec on a Higgs-like binary task (BASELINE
config #2, scaled to single-chip memory).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Extra diagnostic fields (never required by the driver, always best-effort):
``breakdown`` — per-phase device seconds per tree (hist / split / partition /
host+other), ``mfu`` — issued-FLOP utilization estimate for the histogram
phase, ``error`` — present (with value 0.0) only when the backend could not
be brought up after bounded retries, so a flaky boot still emits parseable
JSON instead of a crash.

Each entry runs in its OWN subprocess (``python bench.py --phase NAME``):
a fresh backend per phase means one phase OOMing or crashing the TPU
runtime cannot starve the entries after it (the 20260731T0101Z artifact
lost 10M/join/GLM/breakdown to exactly that cascade — a RESOURCE_EXHAUSTED
in the 10M build poisoned every later allocation in the shared process).
The parent process never touches jax, so the device is free for each child.

Baseline: **measured** (round 5) — sklearn 1.9.0 HistGradientBoosting on the
EXACT headline workload (same generator/rows/depth/bins/min-rows/lr, leaf cap
off, AUC-matched at 0.8452 vs 0.8454) builds 3.52 trees/sec on one pinned
Xeon 2.10 GHz thread on this box (median of 4 OMP_NUM_THREADS=1 fits — the
protocol is IN the script; rep spread 5.54-5.84 s). BASELINE.md records the
box specs and the 16-node-cluster equivalence arithmetic.
vs_baseline = measured / 3.52 (i.e. TPU chip vs one CPU core).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time
import traceback

import numpy as np
import pandas as pd

# row-count scale factor (plumbing tests / constrained windows):
# H2O3_TPU_BENCH_SCALE=0.01 runs every entry at 1% size. Default full size.
_SCALE = float(os.environ.get("H2O3_TPU_BENCH_SCALE", "1"))
N_ROWS = max(int(1_000_000 * _SCALE), 10_000)
N_COLS = 28  # Higgs feature count
N_TREES = 20
DEPTH = 6
BASELINE_TREES_PER_SEC = 3.52  # measured: tools/bench_cpu_baseline.py (BASELINE.md)
INIT_RETRIES = 3
INIT_RETRY_SLEEP_S = 15.0

# Peak dense matmul throughput used for the MFU estimate, by device kind.
# f32 dots run as multi-pass bf16 on the MXU; we report against the bf16 peak
# (the honest ceiling for this formulation).
_PEAK_FLOPS = {
    "v5 lite": 197e12,  # TPU v5e bf16
    "v5e": 197e12,
    "v4": 275e12,
    "cpu": 1e12,  # nominal, so the field stays meaningful on CPU runs
}


def make_data(n=N_ROWS, c=N_COLS, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = (
        1.5 * X[:, 0]
        - X[:, 1]
        + 0.8 * X[:, 2] * X[:, 3]
        + np.sin(2 * X[:, 4])
        + 0.5 * X[:, 5] ** 2
        - 1.0
    )
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-eta))).astype(np.int32)
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(c)])
    df["label"] = np.where(y == 1, "s", "b")
    return df


def _emit(payload: dict) -> None:
    print(json.dumps(payload))


def _last_builder_artifact() -> dict | None:
    """Best committed BENCH_builder_*.json headline — embedded in error
    payloads so a dead tunnel at driver-run time still leaves the verified
    measurement chain visible in the round artifact itself. "Best" = the
    highest real value (A/B control artifacts share a timestamp with their
    main run, so recency alone can pick the slower control)."""
    import glob

    best = None
    here = os.path.dirname(os.path.abspath(__file__))
    for path in glob.glob(os.path.join(here, "BENCH_builder_*.json")):
        name = os.path.basename(path)
        # A/B controls are eligible on purpose: the embedded "file" carries
        # the config suffix (e.g. _noadapt), and defaults move TOWARD the
        # winning config (bin adaptivity was defaulted off after its control
        # run won) — the best committed measurement with its named config is
        # the honest chain pointer
        try:
            with open(path) as f:
                d = json.loads(f.readline())
            if not isinstance(d, dict):
                continue
            v = float(d.get("value") or 0)
            if v > 0 and (best is None or v > best[2]):
                best = (name, d, v)
        except Exception:  # noqa: BLE001 — this runs on the watchdog thread:
            # ANY escape here would skip both the JSON emit and the hard
            # exit, hanging the child forever on a wedged tunnel
            continue
    if best is None:
        return None
    return {"file": best[0], "metric": best[1].get("metric"),
            "value": best[2]}


def _emit_error(stage: str, exc: BaseException) -> None:
    # format_exc only when an exception is actually active (the watchdog
    # constructs its TimeoutError without raising, where format_exc would
    # emit the useless "NoneType: None")
    tb = traceback.format_exc(limit=20) if sys.exc_info()[0] is not None else ""
    payload = {
        "metric": f"GBM trees/sec ({N_ROWS // 1_000_000}M rows x {N_COLS} cols, depth {DEPTH})",
        "value": 0.0,
        "unit": "trees/sec/chip",
        "vs_baseline": 0.0,
        "error": f"{stage}: {exc!r}",
        "traceback": tb,
    }
    last = _last_builder_artifact()
    if last is not None:
        payload["best_builder_artifact"] = last
    _emit(payload)


INIT_WATCHDOG_S = 420.0  # backend init can HANG (dead tunnel), not just fail


def _init_with_retry():
    """Backend bring-up with bounded retry — TPU runtime boot can flake.

    A watchdog covers the hang mode (a wedged tunnel blocks inside
    ``jax.devices()`` forever, which no exception-retry can catch): if init
    hasn't completed within INIT_WATCHDOG_S, the error JSON is emitted and
    the process exits hard, so the driver always gets parseable output.
    """
    import os
    import threading

    import h2o3_tpu

    def _die():
        _emit_error("init", TimeoutError(
            f"backend init hung > {INIT_WATCHDOG_S:.0f}s (tunnel down?)"
        ))
        sys.stdout.flush()
        os._exit(2)

    watchdog = threading.Timer(INIT_WATCHDOG_S, _die)
    watchdog.daemon = True
    watchdog.start()
    try:
        last = None
        for attempt in range(INIT_RETRIES):
            try:
                info = h2o3_tpu.init(log_level="WARN")
                # force a real device round-trip so a half-up backend fails HERE
                import jax
                import jax.numpy as jnp

                jnp.zeros(8).block_until_ready()
                return info
            except Exception as e:  # noqa: BLE001 — any backend error retries
                last = e
                if attempt < INIT_RETRIES - 1:
                    time.sleep(INIT_RETRY_SLEEP_S * (attempt + 1))
        raise RuntimeError(
            f"backend init failed after {INIT_RETRIES} attempts"
        ) from last
    finally:
        watchdog.cancel()


def _phase_breakdown(
    fr, n_trees: int, total_s: float, nbins: int = 255
) -> tuple[dict, float, float]:
    """Time the histogram / split / partition phases standalone on the bench
    data shapes and estimate histogram-phase MFU.

    Returns ({phase: sec_per_tree}, hist_flops_per_tree,
    hist_flops_traced_per_tree). Phases are timed as the same jitted programs
    the level loop runs, summed over the per-level node counts
    1,2,4,...,2^(DEPTH-1); "host_other" is the remainder of the measured
    wall time. ``hist_flops`` prices the standalone direct-scheme programs
    timed here (every node's histogram built — the denominator for "mfu");
    ``hist_flops_traced`` prices the program that actually RAN: with
    H2O3_TPU_HIST_SUBTRACT=1 each level past the root builds only ONE
    sibling per pair (half the frontier) and derives the other by
    subtraction, so crediting the traced ph_hist time with every node's
    FLOPs would overstate mfu_traced ~2x.
    """
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.models.tree.binning import BinSpec, fit_bins, bin_frame
    from h2o3_tpu.ops.histogram import build_histograms
    from h2o3_tpu.parallel.mesh import row_sharding

    cols = [c for c in fr.names if c != "label"]
    spec = fit_bins(fr, cols, nbins=nbins)  # same bins the headline ran at
    bins_u8 = bin_frame(spec, fr)
    n_pad = bins_u8.shape[0]
    n_bins = spec.max_bins

    rng = np.random.default_rng(0)
    w = jax.device_put(jnp.ones(n_pad, jnp.float32), row_sharding())
    wy = jax.device_put(
        jnp.asarray(rng.normal(size=n_pad).astype(np.float32)), row_sharding()
    )

    def timed(f, *args, reps=3):
        out = f(*args)  # warmup/compile
        jax.tree.map(lambda x: x.block_until_ready(), out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(*args)
        jax.tree.map(lambda x: x.block_until_ready(), out)
        return (time.perf_counter() - t0) / reps

    from h2o3_tpu.models.tree.shared_tree import _subtract_enabled

    subtract = _subtract_enabled()
    hist_s = 0.0
    hist_flops = 0.0
    hist_flops_traced = 0.0
    for level in range(DEPTH):
        n_nodes = 2**level
        # nodes whose histogram the fused program actually BUILDS at this
        # level: all of them in the direct scheme; one sibling per pair
        # (half) under subtraction, except the root which has no sibling
        n_built = n_nodes if (level == 0 or not subtract) else n_nodes // 2
        nid = jax.device_put(
            jnp.asarray(rng.integers(0, n_nodes, n_pad).astype(np.int32)),
            row_sharding(),
        )
        hist_s += timed(
            lambda b, n, ww, wwy: build_histograms(
                b, n, (ww, wwy, ww), n_nodes, n_bins),
            bins_u8,
            nid,
            w,
            wy,
        )
        # matmul-path issued FLOPs: 3 stats x 2*n*N*(C*B) per level (the
        # wy2 lane was dropped — its gain contribution cancels exactly)
        hist_flops += 3 * 2.0 * n_pad * n_nodes * len(cols) * n_bins
        hist_flops_traced += 3 * 2.0 * n_pad * n_built * len(cols) * n_bins

    # split scan at the deepest level's node count (the most expensive one)
    from h2o3_tpu.models.tree.shared_tree import _split_scan

    n_nodes = 2 ** (DEPTH - 1)
    hist = jnp.zeros((n_nodes, len(cols), n_bins, 3), jnp.float32).at[:, :, :, 0].set(1.0)
    split_fn = jax.jit(
        lambda h: _split_scan(
            h,
            jnp.zeros(len(cols), bool),
            jnp.ones((n_nodes, len(cols)), jnp.float32),
            jnp.float32(10.0),
            jnp.float32(1e-5),
        )
    )
    split_s = timed(split_fn, hist) * DEPTH  # ~same cost each level

    # partition update: recompute nid children assignment over all rows
    @jax.jit
    def partition(b, n):
        col = jnp.zeros(n_pad, jnp.int32)
        thr = jnp.full(n_pad, 128, jnp.int32)
        bv = jnp.take_along_axis(b.astype(jnp.int32), col[:, None], axis=1)[:, 0]
        return jnp.where(bv <= thr, n * 2, n * 2 + 1)

    nid = jax.device_put(jnp.zeros(n_pad, jnp.int32), row_sharding())
    part_s = timed(partition, bins_u8, nid) * DEPTH

    per_tree = {
        "hist_s": round(hist_s, 4),
        "split_s": round(split_s, 4),
        "partition_s": round(part_s, 4),
    }
    # The training loop runs these phases FUSED in one scanned dispatch per
    # scoring interval; the per-phase numbers above are standalone-dispatch
    # diagnostics (each carries ~66 ms tunnel latency once any D2H transfer
    # has happened). fused_tree_s is the actual per-tree device cost.
    try:
        from h2o3_tpu.models.tree.distributions import grad_hess
        from h2o3_tpu.models.tree.shared_tree import build_trees_scanned

        spec2 = fit_bins(fr, cols)
        t0 = time.perf_counter()
        out = build_trees_scanned(
            bins_u8, w, wy, jnp.zeros(n_pad, jnp.float32),
            jnp.zeros(len(cols), jnp.float32), jax.random.PRNGKey(0), 4,
            grad_fn=lambda F_, y_, w_: grad_hess("bernoulli", F_, y_, w_, 0.0),
            grad_key=("bench", "bernoulli"),
            sample_rate=1.0, n_bins=n_bins, is_cat_cols=spec2.is_cat,
            max_depth=DEPTH, min_rows=10.0, min_split_improvement=1e-5,
            learn_rates=np.full(4, 0.1, np.float32), max_abs_leaf=float("inf"),
            col_sample_rate=1.0, col_sample_rate_per_tree=1.0,
        )
        jax.tree.map(lambda x: x.block_until_ready(), out[0])
        per_tree["fused_compile_s"] = round(time.perf_counter() - t0, 4)
        t0 = time.perf_counter()
        out = build_trees_scanned(
            bins_u8, w, wy, jnp.zeros(n_pad, jnp.float32),
            jnp.zeros(len(cols), jnp.float32), jax.random.PRNGKey(0), 4,
            grad_fn=lambda F_, y_, w_: grad_hess("bernoulli", F_, y_, w_, 0.0),
            grad_key=("bench", "bernoulli"),
            sample_rate=1.0, n_bins=n_bins, is_cat_cols=spec2.is_cat,
            max_depth=DEPTH, min_rows=10.0, min_split_improvement=1e-5,
            learn_rates=np.full(4, 0.1, np.float32), max_abs_leaf=float("inf"),
            col_sample_rate=1.0, col_sample_rate_per_tree=1.0,
        )
        jax.tree.map(lambda x: x.block_until_ready(), out[0])
        per_tree["fused_tree_s"] = round((time.perf_counter() - t0) / 4, 4)
    except Exception as e:
        per_tree["fused_tree_error"] = repr(e)
    device_s = per_tree.get("fused_tree_s", hist_s + split_s + part_s)
    per_tree["host_other_s"] = round(max(total_s / n_trees - device_s, 0.0), 4)
    return per_tree, hist_flops, hist_flops_traced


def _drop_models(*models) -> None:
    """Unregister bench models: a registered model pins its training frame
    through ``params.training_frame``, so DKV.remove(frame) alone does not
    free HBM for the later entries."""
    from h2o3_tpu.cluster.registry import DKV

    for m in models:
        if m is not None:
            DKV.remove(m.key)


def _make_data_device(n: int, c: int = N_COLS, seed: int = 0, labeler=None,
                      col_prefix: str = "f"):
    """Bench frame synthesized ON DEVICE: a 10M-row frame is ~1.2 GB — at
    tunneled-TPU host→device bandwidth the upload alone blew the bench
    budget, and the metrics here are trees/rows per second, not ingest.

    ``labeler(key, X) -> (int8 codes, domain)`` defaults to the same
    Bernoulli generative model as :func:`make_data`."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.frame.frame import CAT, NUM, Frame, Vec
    from h2o3_tpu.parallel.mesh import pad_to_shards, row_sharding

    npad = pad_to_shards(n)

    def _bernoulli(ku, X):
        eta = (1.5 * X[:, 0] - X[:, 1] + 0.8 * X[:, 2] * X[:, 3]
               + jnp.sin(2 * X[:, 4]) + 0.5 * X[:, 5] ** 2 - 1.0)
        u = jax.random.uniform(ku, (X.shape[0],))
        return (u < jax.nn.sigmoid(eta)).astype(jnp.int8), ("b", "s")

    label_fn = labeler or _bernoulli
    domain_box = []

    @functools.partial(jax.jit, out_shardings=row_sharding())
    def gen(key):
        kx, ku = jax.random.split(key)
        X = jax.random.normal(kx, (npad, c), jnp.float32)
        y, domain = label_fn(ku, X)
        domain_box.append(domain)  # trace-time constant
        pad = jnp.arange(npad) >= n
        X = jnp.where(pad[:, None], jnp.nan, X)
        y = jnp.where(pad, -1, y).astype(jnp.int8)
        return X, y

    X, y = gen(jax.random.PRNGKey(seed))
    vecs = [Vec(X[:, i], NUM, name=f"{col_prefix}{i}", nrow=n) for i in range(c)]
    vecs.append(Vec(y, CAT, name="label", nrow=n, domain=domain_box[0]))
    return Frame(vecs, register=True)


def _bench_10m() -> dict:
    """GBM at 10M rows single chip (binned uint8 ≈ 280 MB on device)."""
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.models.tree import GBM

    n10 = int(10_000_000 * _SCALE)
    fr = _make_data_device(n10)
    m0 = m = None
    try:
        kw = dict(max_depth=DEPTH, learn_rate=0.1, min_rows=10.0,
                  score_tree_interval=1000, seed=42)
        m0 = GBM(ntrees=5, **kw).train(y="label", training_frame=fr)  # compile
        t0 = time.time()
        m = GBM(ntrees=5, **kw).train(y="label", training_frame=fr)
        dt = time.time() - t0
        return {
            "rows": n10,
            "trees_per_sec": round(5 / dt, 3),
            "auc": round(float(m.training_metrics.auc), 4),
        }
    finally:
        # failure path too: a leaked 10M frame starves every later entry
        _drop_models(m0, m)
        DKV.remove(fr.key)
        del fr


def _bench_join_10m() -> dict:
    """Device sort-merge join (frame/ops.py merge) at 10M x 1M rows."""
    import h2o3_tpu
    from h2o3_tpu.frame import ops

    import jax
    import jax.numpy as jnp

    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.frame.frame import NUM, Frame, Vec
    from h2o3_tpu.parallel.mesh import pad_to_shards, row_sharding

    def _dev_frame(n, key, kmax, with_x):
        npad = pad_to_shards(n)

        @functools.partial(jax.jit, out_shardings=row_sharding())
        def gen(k):
            kk, kx = jax.random.split(k)
            ks = (jax.random.randint(kk, (npad,), 0, kmax) if with_x
                  else jnp.arange(npad)).astype(jnp.float32)
            xs = jax.random.normal(kx, (npad,), jnp.float32)
            pad = jnp.arange(npad) >= n
            return (jnp.where(pad, jnp.nan, ks), jnp.where(pad, jnp.nan, xs))

        ks, xs = gen(key)
        return Frame([Vec(ks, NUM, name="k", nrow=n),
                      Vec(xs, NUM, name="x" if with_x else "y", nrow=n)],
                     register=True)

    left = right = out = None
    try:
        nl, nr = int(10_000_000 * _SCALE), int(1_000_000 * _SCALE)
        left = _dev_frame(nl, jax.random.PRNGKey(1), nr, True)
        right = _dev_frame(nr, jax.random.PRNGKey(2), nr, False)
        out = ops.merge(left, right, by=["k"])  # warm compile
        t0 = time.time()
        out = ops.merge(left, right, by=["k"])
        dt = time.time() - t0
        return {"left_rows": nl, "right_rows": nr,
                "out_rows": out.nrow, "seconds": round(dt, 3),
                "rows_per_sec": round(out.nrow / dt, 0)}
    finally:
        for fr in (left, right):  # free HBM before the phase breakdown runs
            if fr is not None:
                DKV.remove(fr.key)
        del left, right, out


def _bench_cat_1m() -> dict:
    """GBM on a categorical-heavy frame (BASELINE config #3 workload shape:
    Criteo-style high-cardinality enums + numerics). Exercises the
    mean-sorted categorical split path and enum code storage at scale."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.models.tree import GBM

    n = max(int(1_000_000 * _SCALE), 10_000)
    n_num, n_cat, card = 20, 8, 200

    def labeler(ku, X):
        eta = 1.2 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2] * X[:, 3] - 0.5
        u = jax.random.uniform(ku, (X.shape[0],))
        return (u < jax.nn.sigmoid(eta)).astype(jnp.int8), ("b", "s")

    fr = _make_data_device(n, c=n_num, labeler=labeler)
    fr2 = m0 = m = None
    try:
        # append device-generated enum columns (codes depend on numerics so
        # the categorical splits carry signal)
        from h2o3_tpu.frame.frame import CAT, Frame, Vec

        key = jax.random.PRNGKey(9)
        vecs = [fr.vec(nm) for nm in fr.names]
        for j in range(n_cat):
            kj = jax.random.fold_in(key, j)
            base = fr.vec(f"f{j % n_num}").data
            noise = jax.random.randint(kj, base.shape, 0, card // 4)
            codes = (
                (jnp.abs(jnp.nan_to_num(base)) * 37 + noise) % card
            ).astype(jnp.int16)
            vecs.insert(-1, Vec(codes, CAT, name=f"cat{j}", nrow=n,
                                domain=tuple(f"l{i}" for i in range(card))))
        fr2 = Frame(vecs, register=True)

        kw = dict(max_depth=DEPTH, learn_rate=0.1, min_rows=10.0,
                  score_tree_interval=1000, seed=42)
        m0 = GBM(ntrees=5, **kw).train(y="label", training_frame=fr2)
        t0 = time.time()
        m = GBM(ntrees=5, **kw).train(y="label", training_frame=fr2)
        dt = time.time() - t0
        # compiled group-by (frame/munge.py, ISSUE 20): all value columns'
        # segment stats in ONE mesh-sharded dispatch over the 200-level enum
        from h2o3_tpu.frame import ops

        gb_spec = {"f0": ["sum", "mean"], "f1": ["min", "max"],
                   "f2": ["count", "sd"]}
        ops.group_by(fr2, "cat0").agg(gb_spec)  # warm compile
        t0 = time.time()
        ops.group_by(fr2, "cat0").agg(gb_spec)
        gb_dt = time.time() - t0
        return {
            "rows": n, "num_cols": n_num, "cat_cols": n_cat,
            "cardinality": card, "trees_per_sec": round(5 / dt, 3),
            "auc": round(float(m.training_metrics.auc), 4),
            "groupby_s": round(gb_dt, 3),
            "groupby_rows_per_sec": round(n / max(gb_dt, 1e-9), 0),
        }
    finally:
        _drop_models(m0, m)
        DKV.remove(fr.key)
        if fr2 is not None:
            DKV.remove(fr2.key)


def _bench_dl(n: int = max(int(100_000 * _SCALE), 5_000), d: int = 784, k: int = 10) -> dict:
    """Sync-SGD MLP rows/sec (BASELINE config #4: Hogwild→sync-SGD MLP).
    MNIST-shaped synthetic: 100k x 784 → 10 classes, 2x128 hidden."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.models.deeplearning import DeepLearning

    def labeler(kw, X):
        W = jax.random.normal(kw, (d, k), jnp.float32)
        return (jnp.argmax(X @ W, axis=1).astype(jnp.int8),
                tuple(str(i) for i in range(k)))

    fr = _make_data_device(n, c=d, seed=5, labeler=labeler, col_prefix="p")
    m0 = m = None
    try:
        from h2o3_tpu.utils import metrics as _mx

        kw = dict(hidden=(128, 128), epochs=1.0, mini_batch_size=256, seed=3)
        m0 = DeepLearning(**kw).train(y="label", training_frame=fr)  # compile
        d0 = _mx.counter_value("dl_dispatches_total")
        e0 = _mx.counter_value("dl_epochs_total")
        t0 = time.time()
        m = DeepLearning(**kw).train(y="label", training_frame=fr)
        dt = time.time() - t0
        epochs = int(_mx.counter_value("dl_epochs_total") - e0) or 1
        return {"rows": n, "cols": d, "epochs": 1,
                "rows_per_sec": round(n / dt, 0), "seconds": round(dt, 3),
                # per-round tracked summary (ISSUE 8): wall seconds per
                # epoch plus the chunked-driver dispatch count
                "dl_epoch_s": round(dt / epochs, 3),
                "dispatches_per_model": int(
                    _mx.counter_value("dl_dispatches_total") - d0)}
    finally:
        _drop_models(m0, m)
        DKV.remove(fr.key)
        del fr


def _bench_automl(fr_small) -> dict:
    """AutoML wall-clock (BASELINE secondary metric): max_models budget on a
    50k-row slice of the bench frame.

    Runs the SAME AutoML twice in this fresh process: the first pass pays
    every jit compile its shapes need (``cold_s`` — in-memory caches empty;
    the persistent XLA cache may soften it, so its pre-run entry count is
    recorded), the second hits the warm caches (``warm_s``). cold/warm is
    the VERDICT r4 missing-#5 question: does compile amortize across an
    AutoML run, or dominate it?"""
    import math

    from h2o3_tpu.automl import AutoML

    from h2o3_tpu.models.tree.shared_tree import reset_build_stats

    def run(seed):
        reset_build_stats()
        t0 = time.time()
        aml = AutoML(max_models=3, nfolds=0, seed=seed,
                     max_runtime_secs=900.0, include_algos=["GBM", "GLM"])
        aml.train(y="label", training_frame=fr_small)
        dt = time.time() - t0
        # reset_build_stats snapshots the registry counters (BUILD_STATS is
        # a registry view) — the same values /3/Metrics would serve
        return dt, aml.leaderboard, reset_build_stats()

    cache_entries = _compile_cache_entries()
    cold_s, lb, cold_stats = run(11)
    _drop_models(*(lb.models if lb else ()))
    warm_s, lb, warm_stats = run(11)

    out = {"max_models": 3,
           "cold_s": round(cold_s, 3),
           "warm_s": round(warm_s, 3),
           # per-round tracked summary (ISSUE 8): total AutoML wall time
           # across the cold+warm passes — the end-to-end number the fused
           # GLM/DL lanes must not regress
           "automl_total_s": round(cold_s + warm_s, 3),
           "compile_share_est": round(max(cold_s - warm_s, 0.0) / cold_s, 3)
           if cold_s > 0 else None,
           "persistent_cache_entries_before": cache_entries,
           # shape-bucketed whole-tree amortization (ISSUE 1): the warm pass
           # repeats the cold pass's shapes, so compiled should drop to 0
           # and every tree program come from the in-process cache
           "tree_programs_compiled": [
               cold_stats["tree_programs_compiled"],
               warm_stats["tree_programs_compiled"],
           ],
           "tree_program_cache_hits": [
               cold_stats["tree_program_cache_hits"],
               warm_stats["tree_program_cache_hits"],
           ],
           "dispatches_per_tree": [
               round(s["dispatches"] / max(s["trees_built"], 1), 4)
               for s in (cold_stats, warm_stats)
           ],
           "models_built": len(lb.models) if lb else 0}
    if lb and lb.models:
        auc = float(lb.as_table()[0].get("auc", float("nan")))
        if math.isfinite(auc):  # bare NaN would break the one-line JSON
            out["leader_auc"] = round(auc, 4)
    _drop_models(*(lb.models if lb else ()))
    return out


def _compile_cache_entries() -> int | None:
    """Entry count of the persistent XLA compile cache (None if unset/empty
    dir): distinguishes a truly cold run from one the cache pre-warmed."""
    try:
        from h2o3_tpu import config

        d = config.get("H2O3_TPU_COMPILE_CACHE")
        if not d:
            import h2o3_tpu

            d = os.path.join(os.path.dirname(h2o3_tpu.__file__), ".jax_cache")
        return len(os.listdir(d)) if os.path.isdir(d) else None
    except Exception:  # noqa: BLE001 — diagnostic only
        return None


def _bench_glm_1m(fr) -> dict:
    """GLM binomial IRLS on the bench frame (BASELINE config #1 analog):
    Gram + solve per iteration, the hex.glm hot loop. Reports the fused-
    lane contract numbers (ISSUE 8): measured iterations/sec and host
    dispatches per model from the registry counters — O(iterations/K)
    fused vs O(iterations) unfused."""
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.utils import metrics as _mx

    kw = dict(family="binomial", lambda_=1e-4, max_iterations=20, seed=1)
    GLM(**kw).train(y="label", training_frame=fr)  # compile
    i0 = _mx.counter_value("glm_irls_iterations_total")
    d0 = _mx.counter_value("glm_dispatches_total")
    g0 = sum(_mx.counter_value("tree_collective_bytes_total", phase=ph)
             for ph in ("gram_reduce", "gram_gather"))
    t0 = time.time()
    m = GLM(**kw).train(y="label", training_frame=fr)
    dt = time.time() - t0
    iters = int(_mx.counter_value("glm_irls_iterations_total") - i0) or kw[
        "max_iterations"]
    return {
        "rows": N_ROWS,
        "seconds": round(dt, 3),
        "auc": round(float(m.training_metrics.auc), 4),
        "iterations": iters,
        "glm_iters_per_s": round(iters / max(dt, 1e-9), 3),
        "dispatches_per_model": int(
            _mx.counter_value("glm_dispatches_total") - d0),
        "gram_collective_bytes": round(sum(
            _mx.counter_value("tree_collective_bytes_total", phase=ph)
            for ph in ("gram_reduce", "gram_gather")) - g0, 1),
    }


def _collective_microbench(n_nodes=64, n_bins=128, iters=10) -> dict | None:
    """MEASURED seconds for every hot collective phase at bench shapes —
    the histogram all-reduce vs reduce-scatter + winner gather (trees), the
    Gram reduce-scatter + solve gather (fused GLM), the flat-gradient
    scatter + param gather (sharded DL) — timed as standalone dispatches on
    the real mesh (collectives inside the fused programs cannot be
    host-timed individually; this calibration fills
    ``tree_collective_seconds_total{phase}``). The reduces run through the
    ops/collectives lane, so whatever lane is ACTIVE (quantized,
    hierarchical, exact) is what gets measured — the --quant-ab seconds are
    measured, not modeled. Returns None on a 1-device mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from h2o3_tpu.models.glm import _glm_pad_cols
    from h2o3_tpu.models.tree.shared_tree import _COLL_SECONDS, _split_shard_on
    from h2o3_tpu.ops import collectives
    from h2o3_tpu.parallel.mesh import (
        col_axis_name, get_mesh, n_col_shards, pad_cols_to_shards,
        pad_flat_to_shards, shard_map)

    mesh = get_mesh()
    n_dev = int(mesh.devices.size)
    if n_dev <= 1:
        return None
    # scattered results shard over the COLUMN-BLOCK axis (the whole 1-D
    # mesh, or the cols axis of a 2-D pod mesh — the wrappers run their
    # exact rows-axis stage internally either way)
    cax = col_axis_name(mesh)
    n_blk = n_col_shards(mesh)
    Cp = pad_cols_to_shards(N_COLS, mesh)
    hist = jnp.ones((Cp, n_nodes * n_bins, 3), jnp.float32)  # one local hist
    win = jnp.ones((n_nodes, 14), jnp.float32)  # ~the winner tuple payload
    p_pad = _glm_pad_cols(N_COLS + 1)  # bench GLM design width (+intercept)
    gram = jnp.ones((p_pad, p_pad), jnp.float32)
    # bench DL network (hidden 64x64 on the bench frame) flat param vector
    n_param = (N_COLS * 64 + 64) + (64 * 64 + 64) + (64 + 1)
    grad = jnp.ones((pad_flat_to_shards(n_param, mesh),), jnp.float32)

    def timed(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    sm = lambda f, outs: jax.jit(shard_map(
        f, mesh=mesh, in_specs=(P(),), out_specs=outs, check_vma=False))
    ar_s = timed(sm(
        lambda v: collectives.psum(v, n_dev=n_dev, lane_axis=-1), P()), hist)
    rs_s = timed(sm(
        lambda v: collectives.psum_scatter(v, n_dev=n_dev, lane_axis=-1),
        P(cax)), hist)
    wg_s = timed(sm(lambda v: jax.lax.all_gather(v, cax), P()), win)
    gr_s = timed(sm(
        lambda v: collectives.psum_scatter(v, n_dev=n_dev, passes=2),
        P(cax)), gram)
    gg_s = timed(sm(
        lambda v: jax.lax.all_gather(
            v, cax, axis=0, tiled=True), P()),
        gram.reshape(n_blk, -1)[0])
    dg_s = timed(sm(
        lambda v: collectives.psum_scatter(v, n_dev=n_dev, passes=2),
        P(cax)), grad)
    pg_s = timed(sm(
        lambda v: jax.lax.all_gather(v, cax, axis=0, tiled=True),
        P()), grad.reshape(n_blk, -1)[0])
    sharded = _split_shard_on()
    _COLL_SECONDS.inc(rs_s if sharded else ar_s, phase="hist_reduce")
    if sharded:
        _COLL_SECONDS.inc(wg_s, phase="winner_gather")
    _COLL_SECONDS.inc(gr_s, phase="gram_reduce")
    _COLL_SECONDS.inc(gg_s, phase="gram_gather")
    _COLL_SECONDS.inc(dg_s, phase="dl_grad_reduce")
    _COLL_SECONDS.inc(pg_s, phase="dl_param_gather")
    return {
        "allreduce_s": round(ar_s, 6),
        "reduce_scatter_s": round(rs_s, 6),
        "winner_gather_s": round(wg_s, 6),
        "gram_reduce_s": round(gr_s, 6),
        "gram_gather_s": round(gg_s, 6),
        "dl_grad_reduce_s": round(dg_s, 6),
        "dl_param_gather_s": round(pg_s, 6),
        "mode": "sharded" if sharded else "replicated",
        "lane": "quant" if collectives.quant_enabled() else "exact",
    }


def _phase_headline() -> dict:
    """1M-row GBM trees/sec — the driver's headline metric — plus the
    per-phase breakdown and MFU estimate (same process: they share the
    uploaded frame and the warm compile)."""
    import jax

    import h2o3_tpu
    from h2o3_tpu.models.tree import GBM

    df = make_data()
    fr = h2o3_tpu.upload_file(df)

    kw = dict(
        max_depth=DEPTH,
        learn_rate=0.1,
        min_rows=10.0,
        score_tree_interval=1000,
        seed=42,
    )
    # bin-count A/B knob for TPU windows: the histogram kernel's indicator
    # build is ∝ bins, and 127 quantile bins still exceed upstream's
    # default split resolution (nbins=20)
    from h2o3_tpu.models.tree.binning import MAX_BINS

    nbins_env = os.environ.get("H2O3_TPU_BENCH_NBINS")
    if nbins_env:
        # fit_bins clamps silently — clamp HERE too so the recorded metric
        # label always matches what actually ran
        kw["nbins"] = max(min(int(nbins_env), MAX_BINS), 2)
    # warmup: compile the full configuration (the chunk-scanned builder
    # specializes on chunk length, so warmup must use the same ntrees)
    GBM(ntrees=N_TREES, **kw).train(y="label", training_frame=fr)

    # counters come from the cluster metrics registry — the same numbers
    # GET /3/Metrics serves — so bench artifacts and the live endpoint can
    # never disagree (BUILD_STATS is a view over the same registry)
    from h2o3_tpu.models.tree.shared_tree import reset_build_stats
    from h2o3_tpu.utils import metrics as _mx

    reset_build_stats()
    _coll_phases = ("hist_reduce", "winner_gather")
    _hbm_paths = ("fused", "pallas_unfused", "dense", "fused_via_dense")
    coll_before = {
        ph: _mx.counter_value("tree_collective_bytes_total", phase=ph)
        for ph in _coll_phases
    }
    hbm_before = {
        p: _mx.counter_value("tree_hist_hbm_bytes_total", path=p)
        for p in _hbm_paths
    }
    t0 = time.time()
    m = GBM(ntrees=N_TREES, **kw).train(y="label", training_frame=fr)
    dt = time.time() - t0
    tps = N_TREES / dt
    coll_bytes = {
        ph: _mx.counter_value("tree_collective_bytes_total", phase=ph)
        - coll_before[ph]
        for ph in _coll_phases
    }
    hbm_bytes = {
        p: _mx.counter_value("tree_hist_hbm_bytes_total", path=p)
        - hbm_before[p]
        for p in _hbm_paths
    }
    try:  # measured collective seconds (fills tree_collective_seconds_total)
        coll_s = _collective_microbench()
    except Exception as e:  # noqa: BLE001 — diagnostics never sink the headline
        coll_s = {"error": repr(e)[:120]}
    registry_block = _mx.REGISTRY.compact_snapshot()
    stats = {
        "dispatches": int(_mx.counter_value("tree_dispatches_total")),
        "trees_built": int(_mx.counter_value("tree_trees_built_total")),
        "tree_programs_compiled": int(_mx.counter_value(
            "tree_programs_compiled_total")),
        "tree_program_cache_hits": int(_mx.counter_value(
            "tree_program_cache_hits_total")),
    }
    reset_build_stats()

    payload = {
        # the registry-snapshot block (tools/latest_bench_ok.py requires it)
        "metrics_registry": registry_block,
        "metric": f"GBM trees/sec ({N_ROWS // 1_000_000}M rows x {N_COLS} cols, depth {DEPTH}"
                  + (f", nbins={kw['nbins']}" if "nbins" in kw else "")
                  + f", AUC={m.training_metrics.auc:.4f})",
        "value": round(tps, 3),
        "unit": "trees/sec/chip",
        "vs_baseline": round(tps / BASELINE_TREES_PER_SEC, 3),
        # whole-tree contract (ISSUE 1): O(1) host dispatches per tree —
        # per-level dispatch would read DEPTH+1 here
        "dispatches_per_tree": round(
            stats["dispatches"] / max(stats["trees_built"], 1), 4
        ),
        "tree_programs_compiled": stats["tree_programs_compiled"],
        "tree_program_cache_hits": stats["tree_program_cache_hits"],
        # split-phase collective traffic, from the traced-program byte tally
        # (replication-volume model, ops/histogram.py): the sharded split
        # pipeline's acceptance metric — a sharded run must undercut the
        # replicated control >= 2x at the same shape
        "psum_bytes_per_tree": round(
            sum(coll_bytes.values()) / max(stats["trees_built"], 1), 1
        ),
        "psum_bytes_by_phase": {
            ph: round(v, 1) for ph, v in coll_bytes.items()
        },
        # modeled hist+split HBM traffic (traced-structure tally,
        # tree_hist_hbm_bytes_total): the fused Pallas pipeline's
        # acceptance metric — a fused run must undercut the
        # H2O3_TPU_SPLIT_FUSE=0 control >= 2x at the same shape
        "hist_hbm_bytes_per_tree": round(
            sum(hbm_bytes.values()) / max(stats["trees_built"], 1), 1
        ),
        "hist_hbm_bytes_by_path": {
            p: round(v, 1) for p, v in hbm_bytes.items() if v
        },
    }
    if coll_s is not None:
        payload["collective_s"] = coll_s
    kind = jax.devices()[0].device_kind.lower()
    peak = next((v for k, v in _PEAK_FLOPS.items() if k in kind), None)
    hist_flops = None
    hist_flops_traced = None
    try:
        breakdown, hist_flops, hist_flops_traced = _phase_breakdown(
            fr, N_TREES, dt, nbins=kw.get("nbins", MAX_BINS))
        payload["breakdown"] = breakdown
        if peak is not None and breakdown["hist_s"] > 0:
            payload["mfu"] = round(hist_flops / breakdown["hist_s"] / peak, 4)
        elif peak is None:
            payload["mfu_peak_unknown"] = kind
        payload["device_kind"] = jax.devices()[0].device_kind
    except Exception as e:  # diagnostics must never sink the headline number
        payload["breakdown_error"] = repr(e)
    # trace-based breakdown of the program that actually RAN (VERDICT r4
    # weak #2): phase shares from a jax profiler trace of one more train,
    # attributed via the ph_* named scopes. Requires the HLO dump that
    # _child_main arranged before backend init.
    try:
        import profile_fused  # path added by _child_main

        dump_dir = os.environ.get(profile_fused._DUMP_ENV)
        if dump_dir:
            prof = profile_fused.trace_phases(
                lambda: GBM(ntrees=N_TREES, **kw).train(
                    y="label", training_frame=fr
                ),
                dump_dir,
            )
            payload["fused_profile"] = prof
            if (
                peak is not None
                and hist_flops_traced is not None
                and prof.get("phases_s", {}).get("ph_hist", 0) > 0
            ):
                # phases_s is a PER-DEVICE mean and hist_flops_traced is the
                # whole mesh's work AS THE TRACED PROGRAM ISSUES IT (under
                # H2O3_TPU_HIST_SUBTRACT=1 only the actually-built sibling
                # histograms count): each of n_devices chips does ~1/n
                per_dev_flops = (
                    hist_flops_traced * N_TREES
                    / max(prof.get("n_devices", 1), 1)
                )
                payload["mfu_traced"] = round(
                    per_dev_flops / prof["phases_s"]["ph_hist"] / peak, 4
                )
            profile_fused.cleanup_dump_dir()
    except Exception as e:
        payload["fused_profile_error"] = repr(e)
    return payload


def _bench_hash_1m() -> dict:
    """GLM over feature-hashed 10^6-cardinality enums (BASELINE config #3's
    Criteo shape): proves the hashed path trains with BOUNDED design-matrix
    HBM at any cardinality (VERDICT r4 missing #4). Levels follow a hot-set
    + uniform-tail mixture (Criteo-like skew) with label signal on the hot
    levels, so the AUC shows the hashed representation actually learns."""
    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from h2o3_tpu.frame.frame import CAT, NUM, Frame, Vec
    from h2o3_tpu.models.glm import GLM

    from h2o3_tpu.parallel.mesh import pad_to_shards, row_sharding

    n = max(int(1_000_000 * _SCALE), 10_000)
    card, n_hot, buckets = 1_000_000, 1_000, 256
    npad = pad_to_shards(n)

    @functools.partial(jax.jit, out_shardings=row_sharding())
    def gen(key):
        k1, k2, k3, k4, k5 = jax.random.split(key, 5)
        x0 = jax.random.normal(k1, (npad,), jnp.float32)
        # 90% of rows draw from n_hot hot levels, 10% from the 10^6 tail
        hot = jax.random.randint(k2, (npad,), 0, n_hot)
        tail = jax.random.randint(k3, (npad,), n_hot, card)
        is_hot = jax.random.uniform(k4, (npad,)) < 0.9
        codes = jnp.where(is_hot, hot, tail).astype(jnp.int32)
        eta = 1.2 * x0 + jnp.where(is_hot & (hot % 2 == 0), 1.0, -0.3)
        y = (jax.random.uniform(k5, (npad,)) < jax.nn.sigmoid(eta))
        pad = jnp.arange(npad) >= n
        return (
            jnp.where(pad, jnp.nan, x0),
            jnp.where(pad, -1, codes),
            jnp.where(pad, -1, y.astype(jnp.int8)),
        )

    x0, codes, y = gen(jax.random.PRNGKey(17))
    domain = tuple(f"v{i}" for i in range(card))
    vecs = [
        Vec(x0, NUM, name="x0", nrow=n),
        Vec(codes, CAT, name="c0", nrow=n, domain=domain),
        Vec(y, CAT, name="label", nrow=n, domain=("b", "s")),
    ]
    fr = Frame(vecs, register=True)

    kw = dict(family="binomial", lambda_=1e-4, max_iterations=8,
              hash_buckets=buckets)
    GLM(**kw).train(y="label", training_frame=fr)  # warm/compile
    t0 = time.time()
    m = GLM(**kw).train(y="label", training_frame=fr)
    dt = time.time() - t0
    out = {
        "rows": n,
        "cardinality": card,
        "hash_buckets": buckets,
        # GLM fits with use_all_factor_levels=False: bucket 0 is the
        # reference level, + x0 + intercept
        "ncols_expanded": (buckets - 1) + 2,
        "seconds": round(dt, 3),
        "auc": round(float(m.training_metrics.auc), 4),
    }
    # trees on the SAME 10^6-level enum: the binned path tail-clamps past
    # the bin budget (MIGRATION.md scale-limits #2) — prove it trains with
    # bounded HBM too, and record what clamping costs in AUC. The GLM
    # result must survive ANY tree failure mode, including the parent
    # killing this child at the phase budget: emit the GLM-only payload NOW
    # (the parent keeps the LAST parseable stdout line, and its timeout
    # path reads the killed child's captured stdout).
    _emit(out)
    try:
        from h2o3_tpu.models.tree import GBM

        gkw = dict(ntrees=5, max_depth=DEPTH, learn_rate=0.1, min_rows=10.0,
                   score_tree_interval=1000, seed=42)
        GBM(**gkw).train(y="label", training_frame=fr)  # warm
        t0 = time.time()
        gm = GBM(**gkw).train(y="label", training_frame=fr)
        out["gbm_trees_per_sec"] = round(gkw["ntrees"] / (time.time() - t0), 3)
        out["gbm_auc"] = round(float(gm.training_metrics.auc), 4)
    except Exception as e:  # noqa: BLE001 — diagnostics only
        out["gbm_error"] = repr(e)
    _emit(out)  # GLM+GBM survive a DL failure/kill the same way
    # DL over the hashed block — BASELINE config #4's Criteo-CTR shape
    # (sparse categorical CTR via sync-SGD MLP); hash_buckets bounds the
    # input layer exactly as it bounds the GLM design matrix
    try:
        from h2o3_tpu.models.deeplearning import DeepLearning

        dkw = dict(hidden=[64, 32], epochs=1, mini_batch_size=1024,
                   hash_buckets=buckets, seed=7)
        DeepLearning(**dkw).train(y="label", training_frame=fr)  # warm/compile
        t0 = time.time()
        dm = DeepLearning(**dkw).train(y="label", training_frame=fr)
        ddt = time.time() - t0
        out["dl_seconds"] = round(ddt, 3)
        out["dl_rows_per_sec"] = round(n / max(ddt, 1e-9), 1)
        out["dl_auc"] = round(float(dm.training_metrics.auc), 4)
    except Exception as e:  # noqa: BLE001 — diagnostics only
        out["dl_error"] = repr(e)
    return out


def _phase_glm_1m() -> dict:
    """GLM IRLS at 1M rows (BASELINE config #1: Airlines-1M analog)."""
    import h2o3_tpu

    fr = h2o3_tpu.upload_file(make_data())
    return _bench_glm_1m(fr)


def _phase_automl_50k() -> dict:
    import h2o3_tpu

    small = h2o3_tpu.upload_file(make_data().iloc[: max(int(50_000 * _SCALE), 5_000)])
    return _bench_automl(small)


# name -> (runner, parent-side wall budget seconds). Budgets are generous —
# each child pays its own backend init (~30 s through the tunnel) + compile.
_PHASES: dict = {
    "headline": (_phase_headline, 1500),
    "scale_10m": (_bench_10m, 900),       # VERDICT r4: evidence beyond 1M
    "cat_1m": (_bench_cat_1m, 900),       # BASELINE config #3 workload shape
    "join_10m": (_bench_join_10m, 600),   # ASTMerge successor at scale
    "glm_1m": (_phase_glm_1m, 600),
    "hash_1m": (_bench_hash_1m, 900),     # Criteo-cardinality hashed enums (+GBM)
    "dl_100k": (_bench_dl, 600),          # sync-SGD MLP (BASELINE config #4)
    "automl_50k": (_phase_automl_50k, 1800),  # cold + warm passes
}
# stop launching new phases past this parent deadline so the driver's own
# timeout never truncates the output mid-line
DEADLINE_S = float(os.environ.get("H2O3_TPU_BENCH_DEADLINE_S", 3000))


def _devmem_block() -> dict:
    """Per-phase HBM attribution snapshot (utils/devmem.py): live + peak
    bytes per owning residency plane, and the device in_use/unattributed
    split when the backend reports memory_stats. Every phase subprocess
    embeds one, so the artifact shows peak-per-owner-PER-PHASE — the
    number the TPU-window A/Bs compare against the static capacity model
    (tools/tpu_mem_analysis.py --live is the interactive twin)."""
    from h2o3_tpu.utils import devmem

    devmem.poll(force=True)
    s = devmem.status()
    out = {
        "owned_bytes": s["owned_bytes"],
        "peak_owned_bytes": s["peak_owned_bytes"],
    }
    for k in ("in_use_bytes", "limit_bytes", "unattributed_bytes"):
        if s.get(k) is not None:
            out[k] = s[k]
    return out


def _ledger_block() -> dict:
    """Per-job resource ledgers accumulated in this phase subprocess
    (utils/jobacct.py): device-seconds + dispatch counts by site,
    collective bytes by lane, frame-window bytes, queue waits — keyed by
    job id. The artifact twin of the ``/3/Jobs`` ledger embed; every
    phase's training runs as a Job, so this shows which job spent the
    phase's device time. latest_bench_ok pins the totals as finite and
    bounded by the phase wall."""
    from h2o3_tpu.utils import jobacct

    return jobacct.all_jobs()


def _child_main(phase: str) -> None:
    """Run one phase in this (fresh) process; print its JSON dict."""
    try:
        if phase == "headline":
            # arrange the XLA HLO dump BEFORE jax loads, so the fused-profile
            # trace (tools/profile_fused.py) can attribute ops to phases
            try:
                sys.path.insert(
                    0,
                    os.path.join(
                        os.path.dirname(os.path.abspath(__file__)), "tools"
                    ),
                )
                import profile_fused

                profile_fused.prepare_dump_dir()
            except Exception:  # profiling prep must never sink the headline
                pass
        _init_with_retry()
        out = _PHASES[phase][0]()
        if isinstance(out, dict):
            try:
                out["devmem"] = _devmem_block()
            except Exception:  # noqa: BLE001 — diagnostics never sink a phase
                pass
            try:
                led = _ledger_block()
                if led:
                    out["jobs"] = led
            except Exception:  # noqa: BLE001 — diagnostics never sink a phase
                pass
    except Exception as e:
        tb = traceback.format_exc(limit=20)
        out = {"error": repr(e), "traceback": tb}
    _emit(out)


def _run_phase_subprocess(phase: str, timeout_s: float) -> dict:
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--phase", phase],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        # a killed child may still have emitted partial results (hash_1m
        # emits its GLM payload before attempting GBM) — keep them
        for line in reversed((e.stdout or "").strip().splitlines()):
            try:
                d = json.loads(line)
                if isinstance(d, dict):
                    d.setdefault(
                        "note", f"partial: phase killed at {timeout_s:.0f}s"
                    )
                    return d
            except json.JSONDecodeError:
                continue
        return {"error": f"phase timed out after {timeout_s:.0f}s (parent kill)"}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            d = json.loads(line)
            if isinstance(d, dict):
                return d
        except json.JSONDecodeError:
            continue
    return {
        "error": f"no JSON from phase (rc={proc.returncode})",
        "stderr_tail": proc.stderr[-800:],
    }


def main() -> None:
    if "--phase" in sys.argv:
        _child_main(sys.argv[sys.argv.index("--phase") + 1])
        return

    t_start = time.time()
    payload: dict = {}
    init_down = None
    for phase, (_, budget) in _PHASES.items():
        if phase != "headline" and time.time() - t_start > DEADLINE_S:
            payload[f"{phase}_error"] = "skipped: parent deadline reached"
            continue
        if init_down is not None:
            # a wedged tunnel hangs EVERY child's backend init for the full
            # 420 s watchdog — don't burn it five more times
            payload[f"{phase}_error"] = f"skipped: {init_down}"
            continue
        out = _run_phase_subprocess(phase, budget)
        # progress breadcrumb on stderr: if the wrapper (driver / backlog
        # timeout) kills this parent before the final stdout line, the
        # per-phase results still exist in the captured log
        print(f"[bench] {phase}: {json.dumps(out)}",
              file=sys.stderr, flush=True)
        if isinstance(out.get("error"), str) and "init" in out["error"] and (
            "hung" in out["error"] or "failed after" in out["error"]
        ):
            init_down = "backend init hung/failed in an earlier phase"
        err = out.pop("error", None)
        if phase == "headline":
            if err is not None:
                # headline child failed: preserve the driver contract
                # (metric/value/unit always present and parseable)
                payload.update(
                    {
                        "metric": f"GBM trees/sec ({N_ROWS // 1_000_000}M rows x {N_COLS} cols, depth {DEPTH})",
                        "value": 0.0,
                        "unit": "trees/sec/chip",
                        "vs_baseline": 0.0,
                        "error": err,
                        "traceback": out.get("traceback", ""),
                    }
                )
                # the child already embedded it on the watchdog path;
                # recompute only when the failure mode skipped that
                last = out.get("best_builder_artifact") or _last_builder_artifact()
                if last is not None:
                    payload["best_builder_artifact"] = last
            else:
                payload.update(out)
        elif err is not None:
            payload[f"{phase}_error"] = err
        else:
            out.pop("traceback", None)
            payload[phase] = out
    # tracked per-round summary (ISSUE 8 / ROADMAP item 5): lift the
    # GLM/DL/AutoML phase numbers to headline keys so the round-over-round
    # artifact diff shows the whole-program gains at a glance
    # (tools/latest_bench_ok.py sanity-checks them when present)
    for phase, k in (("glm_1m", "glm_iters_per_s"),
                     ("dl_100k", "dl_epoch_s"),
                     ("automl_50k", "automl_total_s")):
        ph = payload.get(phase)
        if isinstance(ph, dict) and ph.get(k) is not None:
            payload[k] = ph[k]
    _emit(payload)


if __name__ == "__main__":
    main()
