"""North-star benchmark: GBM trees/sec on a Higgs-like binary task (BASELINE
config #2, scaled to single-chip memory).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: h2o-3's CPU GBM builds ~0.5-1.5 trees/sec at depth 6-10 on 1M-row
Higgs-class data on a multicore x86 node (external szilard/GBM-perf context,
BASELINE.md — the reference repo publishes no numbers and the mount was
empty). We use 1.0 trees/sec as the 1M-row single-node reference point;
vs_baseline = measured/1.0.
"""

from __future__ import annotations

import json
import time

import numpy as np
import pandas as pd

N_ROWS = 1_000_000
N_COLS = 28  # Higgs feature count
N_TREES = 20
DEPTH = 6
BASELINE_TREES_PER_SEC = 1.0


def make_data(n=N_ROWS, c=N_COLS, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = (
        1.5 * X[:, 0]
        - X[:, 1]
        + 0.8 * X[:, 2] * X[:, 3]
        + np.sin(2 * X[:, 4])
        + 0.5 * X[:, 5] ** 2
        - 1.0
    )
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-eta))).astype(np.int32)
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(c)])
    df["label"] = np.where(y == 1, "s", "b")
    return df


def main() -> None:
    import h2o3_tpu
    from h2o3_tpu.models.tree import GBM

    h2o3_tpu.init(log_level="WARN")
    df = make_data()
    fr = h2o3_tpu.upload_file(df)

    kw = dict(
        max_depth=DEPTH,
        learn_rate=0.1,
        min_rows=10.0,
        score_tree_interval=1000,
        seed=42,
    )
    # warmup: compile all level shapes
    GBM(ntrees=2, **kw).train(y="label", training_frame=fr)

    t0 = time.time()
    m = GBM(ntrees=N_TREES, **kw).train(y="label", training_frame=fr)
    dt = time.time() - t0
    tps = N_TREES / dt

    print(
        json.dumps(
            {
                "metric": f"GBM trees/sec ({N_ROWS // 1_000_000}M rows x {N_COLS} cols, depth {DEPTH}, AUC={m.training_metrics.auc:.4f})",
                "value": round(tps, 3),
                "unit": "trees/sec/chip",
                "vs_baseline": round(tps / BASELINE_TREES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
