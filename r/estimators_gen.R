# GENERATED FILE — do not edit. Regenerate with tools/gen_bindings.py.
#
# Explicit per-algorithm h2o.* training functions with every parameter as a
# named argument with its default (the gen_R.py codegen analog, SURVEY.md
# §2.3 [UNVERIFIED upstream path h2o-bindings/bin/gen_R.py]). Requires
# h2o3tpu.R to be sourced first (.h2o.req / .h2o.train helpers). Only
# arguments the caller actually supplies are sent to the server (missing()
# check), so server-side defaults stay authoritative.

.h2o.train_params <- function(algo, y, x, training_frame, validation_frame,
                              params) {
  stopifnot(inherits(training_frame, "H2O3Frame"))
  # delegate to h2o3tpu.R's .h2o.train so job-wait / model-resolution
  # logic lives in exactly one place
  do.call(.h2o.train, c(
    list(algo, y = y, x = x, training_frame = training_frame,
         validation_frame = validation_frame),
    params))
}

h2o.gbm <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    ntrees = 50,
    max_depth = 5,
    min_rows = 10.0,
    nbins = 255,
    nbins_cats = 1024,
    nbins_top_level = 1024,
    min_split_improvement = 1e-05,
    sample_rate = 1.0,
    col_sample_rate_per_tree = 1.0,
    score_tree_interval = 5,
    grow_policy = "depthwise",
    max_leaves = 0,
    calibrate_model = FALSE,
    calibration_frame = NULL,
    calibration_method = "AUTO",
    learn_rate = 0.1,
    learn_rate_annealing = 1.0,
    distribution = "AUTO",
    col_sample_rate = 1.0,
    max_abs_leafnode_pred = Inf,
    quantile_alpha = 0.5,
    tweedie_power = 1.5,
    huber_alpha = 0.9,
    monotone_constraints = NULL
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(min_rows)) p$min_rows <- min_rows
  if (!missing(nbins)) p$nbins <- nbins
  if (!missing(nbins_cats)) p$nbins_cats <- nbins_cats
  if (!missing(nbins_top_level)) p$nbins_top_level <- nbins_top_level
  if (!missing(min_split_improvement)) p$min_split_improvement <- min_split_improvement
  if (!missing(sample_rate)) p$sample_rate <- sample_rate
  if (!missing(col_sample_rate_per_tree)) p$col_sample_rate_per_tree <- col_sample_rate_per_tree
  if (!missing(score_tree_interval)) p$score_tree_interval <- score_tree_interval
  if (!missing(grow_policy)) p$grow_policy <- grow_policy
  if (!missing(max_leaves)) p$max_leaves <- max_leaves
  if (!missing(calibrate_model)) p$calibrate_model <- calibrate_model
  if (!missing(calibration_frame)) p$calibration_frame <- calibration_frame
  if (!missing(calibration_method)) p$calibration_method <- calibration_method
  if (!missing(learn_rate)) p$learn_rate <- learn_rate
  if (!missing(learn_rate_annealing)) p$learn_rate_annealing <- learn_rate_annealing
  if (!missing(distribution)) p$distribution <- distribution
  if (!missing(col_sample_rate)) p$col_sample_rate <- col_sample_rate
  if (!missing(max_abs_leafnode_pred)) p$max_abs_leafnode_pred <- max_abs_leafnode_pred
  if (!missing(quantile_alpha)) p$quantile_alpha <- quantile_alpha
  if (!missing(tweedie_power)) p$tweedie_power <- tweedie_power
  if (!missing(huber_alpha)) p$huber_alpha <- huber_alpha
  if (!missing(monotone_constraints)) p$monotone_constraints <- monotone_constraints
  .h2o.train_params("gbm", y, x, training_frame, validation_frame, p)
}

h2o.xgboost <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    ntrees = 50,
    max_depth = 6,
    min_rows = 1.0,
    nbins = 255,
    nbins_cats = 1024,
    nbins_top_level = 1024,
    min_split_improvement = 0.0,
    sample_rate = 1.0,
    col_sample_rate_per_tree = 1.0,
    score_tree_interval = 5,
    grow_policy = "depthwise",
    max_leaves = 0,
    calibrate_model = FALSE,
    calibration_frame = NULL,
    calibration_method = "AUTO",
    learn_rate = 0.3,
    learn_rate_annealing = 1.0,
    distribution = "AUTO",
    col_sample_rate = 1.0,
    max_abs_leafnode_pred = Inf,
    quantile_alpha = 0.5,
    tweedie_power = 1.5,
    huber_alpha = 0.9,
    monotone_constraints = NULL,
    reg_lambda = 1.0,
    reg_alpha = 0.0,
    tree_method = "auto",
    booster = "gbtree",
    scale_pos_weight = 1.0,
    dmatrix_type = "auto"
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(min_rows)) p$min_rows <- min_rows
  if (!missing(nbins)) p$nbins <- nbins
  if (!missing(nbins_cats)) p$nbins_cats <- nbins_cats
  if (!missing(nbins_top_level)) p$nbins_top_level <- nbins_top_level
  if (!missing(min_split_improvement)) p$min_split_improvement <- min_split_improvement
  if (!missing(sample_rate)) p$sample_rate <- sample_rate
  if (!missing(col_sample_rate_per_tree)) p$col_sample_rate_per_tree <- col_sample_rate_per_tree
  if (!missing(score_tree_interval)) p$score_tree_interval <- score_tree_interval
  if (!missing(grow_policy)) p$grow_policy <- grow_policy
  if (!missing(max_leaves)) p$max_leaves <- max_leaves
  if (!missing(calibrate_model)) p$calibrate_model <- calibrate_model
  if (!missing(calibration_frame)) p$calibration_frame <- calibration_frame
  if (!missing(calibration_method)) p$calibration_method <- calibration_method
  if (!missing(learn_rate)) p$learn_rate <- learn_rate
  if (!missing(learn_rate_annealing)) p$learn_rate_annealing <- learn_rate_annealing
  if (!missing(distribution)) p$distribution <- distribution
  if (!missing(col_sample_rate)) p$col_sample_rate <- col_sample_rate
  if (!missing(max_abs_leafnode_pred)) p$max_abs_leafnode_pred <- max_abs_leafnode_pred
  if (!missing(quantile_alpha)) p$quantile_alpha <- quantile_alpha
  if (!missing(tweedie_power)) p$tweedie_power <- tweedie_power
  if (!missing(huber_alpha)) p$huber_alpha <- huber_alpha
  if (!missing(monotone_constraints)) p$monotone_constraints <- monotone_constraints
  if (!missing(reg_lambda)) p$reg_lambda <- reg_lambda
  if (!missing(reg_alpha)) p$reg_alpha <- reg_alpha
  if (!missing(tree_method)) p$tree_method <- tree_method
  if (!missing(booster)) p$booster <- booster
  if (!missing(scale_pos_weight)) p$scale_pos_weight <- scale_pos_weight
  if (!missing(dmatrix_type)) p$dmatrix_type <- dmatrix_type
  .h2o.train_params("xgboost", y, x, training_frame, validation_frame, p)
}

h2o.randomForest <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    ntrees = 50,
    max_depth = 20,
    min_rows = 1.0,
    nbins = 255,
    nbins_cats = 1024,
    nbins_top_level = 1024,
    min_split_improvement = 1e-05,
    sample_rate = 0.632,
    col_sample_rate_per_tree = 1.0,
    score_tree_interval = 5,
    grow_policy = "depthwise",
    max_leaves = 0,
    calibrate_model = FALSE,
    calibration_frame = NULL,
    calibration_method = "AUTO",
    mtries = -1,
    binomial_double_trees = FALSE
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(min_rows)) p$min_rows <- min_rows
  if (!missing(nbins)) p$nbins <- nbins
  if (!missing(nbins_cats)) p$nbins_cats <- nbins_cats
  if (!missing(nbins_top_level)) p$nbins_top_level <- nbins_top_level
  if (!missing(min_split_improvement)) p$min_split_improvement <- min_split_improvement
  if (!missing(sample_rate)) p$sample_rate <- sample_rate
  if (!missing(col_sample_rate_per_tree)) p$col_sample_rate_per_tree <- col_sample_rate_per_tree
  if (!missing(score_tree_interval)) p$score_tree_interval <- score_tree_interval
  if (!missing(grow_policy)) p$grow_policy <- grow_policy
  if (!missing(max_leaves)) p$max_leaves <- max_leaves
  if (!missing(calibrate_model)) p$calibrate_model <- calibrate_model
  if (!missing(calibration_frame)) p$calibration_frame <- calibration_frame
  if (!missing(calibration_method)) p$calibration_method <- calibration_method
  if (!missing(mtries)) p$mtries <- mtries
  if (!missing(binomial_double_trees)) p$binomial_double_trees <- binomial_double_trees
  .h2o.train_params("drf", y, x, training_frame, validation_frame, p)
}

h2o.xrt <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    ntrees = 50,
    max_depth = 20,
    min_rows = 1.0,
    nbins = 255,
    nbins_cats = 1024,
    nbins_top_level = 1024,
    min_split_improvement = 1e-05,
    sample_rate = 0.632,
    col_sample_rate_per_tree = 1.0,
    score_tree_interval = 5,
    grow_policy = "depthwise",
    max_leaves = 0,
    calibrate_model = FALSE,
    calibration_frame = NULL,
    calibration_method = "AUTO",
    mtries = -1,
    binomial_double_trees = FALSE
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(min_rows)) p$min_rows <- min_rows
  if (!missing(nbins)) p$nbins <- nbins
  if (!missing(nbins_cats)) p$nbins_cats <- nbins_cats
  if (!missing(nbins_top_level)) p$nbins_top_level <- nbins_top_level
  if (!missing(min_split_improvement)) p$min_split_improvement <- min_split_improvement
  if (!missing(sample_rate)) p$sample_rate <- sample_rate
  if (!missing(col_sample_rate_per_tree)) p$col_sample_rate_per_tree <- col_sample_rate_per_tree
  if (!missing(score_tree_interval)) p$score_tree_interval <- score_tree_interval
  if (!missing(grow_policy)) p$grow_policy <- grow_policy
  if (!missing(max_leaves)) p$max_leaves <- max_leaves
  if (!missing(calibrate_model)) p$calibrate_model <- calibrate_model
  if (!missing(calibration_frame)) p$calibration_frame <- calibration_frame
  if (!missing(calibration_method)) p$calibration_method <- calibration_method
  if (!missing(mtries)) p$mtries <- mtries
  if (!missing(binomial_double_trees)) p$binomial_double_trees <- binomial_double_trees
  .h2o.train_params("xrt", y, x, training_frame, validation_frame, p)
}

h2o.glm <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    family = "AUTO",
    link = "family_default",
    solver = "AUTO",
    alpha = NULL,
    lambda = NULL,
    lambda_search = FALSE,
    nlambdas = -1,
    lambda_min_ratio = -1.0,
    standardize = TRUE,
    intercept = TRUE,
    max_iterations = -1,
    beta_epsilon = 0.0001,
    objective_epsilon = 1e-06,
    tweedie_variance_power = 0.0,
    tweedie_link_power = 1.0,
    theta = 1e-05,
    missing_values_handling = "mean_imputation",
    compute_p_values = FALSE,
    non_negative = FALSE,
    interactions = NULL,
    interaction_pairs = NULL,
    hash_buckets = NULL
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(family)) p$family <- family
  if (!missing(link)) p$link <- link
  if (!missing(solver)) p$solver <- solver
  if (!missing(alpha)) p$alpha <- alpha
  if (!missing(lambda)) p$lambda <- lambda
  if (!missing(lambda_search)) p$lambda_search <- lambda_search
  if (!missing(nlambdas)) p$nlambdas <- nlambdas
  if (!missing(lambda_min_ratio)) p$lambda_min_ratio <- lambda_min_ratio
  if (!missing(standardize)) p$standardize <- standardize
  if (!missing(intercept)) p$intercept <- intercept
  if (!missing(max_iterations)) p$max_iterations <- max_iterations
  if (!missing(beta_epsilon)) p$beta_epsilon <- beta_epsilon
  if (!missing(objective_epsilon)) p$objective_epsilon <- objective_epsilon
  if (!missing(tweedie_variance_power)) p$tweedie_variance_power <- tweedie_variance_power
  if (!missing(tweedie_link_power)) p$tweedie_link_power <- tweedie_link_power
  if (!missing(theta)) p$theta <- theta
  if (!missing(missing_values_handling)) p$missing_values_handling <- missing_values_handling
  if (!missing(compute_p_values)) p$compute_p_values <- compute_p_values
  if (!missing(non_negative)) p$non_negative <- non_negative
  if (!missing(interactions)) p$interactions <- interactions
  if (!missing(interaction_pairs)) p$interaction_pairs <- interaction_pairs
  if (!missing(hash_buckets)) p$hash_buckets <- hash_buckets
  .h2o.train_params("glm", y, x, training_frame, validation_frame, p)
}

h2o.deeplearning <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    hidden = c(200, 200),
    epochs = 10.0,
    activation = "Rectifier",
    input_dropout_ratio = 0.0,
    hidden_dropout_ratios = NULL,
    l1 = 0.0,
    l2 = 0.0,
    adaptive_rate = TRUE,
    rho = 0.99,
    epsilon = 1e-08,
    rate = 0.005,
    rate_decay = 1.0,
    momentum_start = 0.0,
    mini_batch_size = 32,
    standardize = TRUE,
    loss = "Automatic",
    reproducible = TRUE,
    autoencoder = FALSE,
    hash_buckets = NULL
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(hidden)) p$hidden <- hidden
  if (!missing(epochs)) p$epochs <- epochs
  if (!missing(activation)) p$activation <- activation
  if (!missing(input_dropout_ratio)) p$input_dropout_ratio <- input_dropout_ratio
  if (!missing(hidden_dropout_ratios)) p$hidden_dropout_ratios <- hidden_dropout_ratios
  if (!missing(l1)) p$l1 <- l1
  if (!missing(l2)) p$l2 <- l2
  if (!missing(adaptive_rate)) p$adaptive_rate <- adaptive_rate
  if (!missing(rho)) p$rho <- rho
  if (!missing(epsilon)) p$epsilon <- epsilon
  if (!missing(rate)) p$rate <- rate
  if (!missing(rate_decay)) p$rate_decay <- rate_decay
  if (!missing(momentum_start)) p$momentum_start <- momentum_start
  if (!missing(mini_batch_size)) p$mini_batch_size <- mini_batch_size
  if (!missing(standardize)) p$standardize <- standardize
  if (!missing(loss)) p$loss <- loss
  if (!missing(reproducible)) p$reproducible <- reproducible
  if (!missing(autoencoder)) p$autoencoder <- autoencoder
  if (!missing(hash_buckets)) p$hash_buckets <- hash_buckets
  .h2o.train_params("deeplearning", y, x, training_frame, validation_frame, p)
}

h2o.kmeans <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    k = 2,
    max_iterations = 10,
    init = "Furthest",
    standardize = TRUE,
    estimate_k = FALSE
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(k)) p$k <- k
  if (!missing(max_iterations)) p$max_iterations <- max_iterations
  if (!missing(init)) p$init <- init
  if (!missing(standardize)) p$standardize <- standardize
  if (!missing(estimate_k)) p$estimate_k <- estimate_k
  .h2o.train_params("kmeans", y, x, training_frame, validation_frame, p)
}

h2o.prcomp <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    k = 1,
    transform = "STANDARDIZE",
    pca_method = "GramSVD",
    use_all_factor_levels = FALSE
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(k)) p$k <- k
  if (!missing(transform)) p$transform <- transform
  if (!missing(pca_method)) p$pca_method <- pca_method
  if (!missing(use_all_factor_levels)) p$use_all_factor_levels <- use_all_factor_levels
  .h2o.train_params("pca", y, x, training_frame, validation_frame, p)
}

h2o.svd <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    nv = 1,
    transform = "NONE",
    svd_method = "Randomized",
    max_iterations = 4
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(nv)) p$nv <- nv
  if (!missing(transform)) p$transform <- transform
  if (!missing(svd_method)) p$svd_method <- svd_method
  if (!missing(max_iterations)) p$max_iterations <- max_iterations
  .h2o.train_params("svd", y, x, training_frame, validation_frame, p)
}

h2o.naiveBayes <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    laplace = 0.0,
    min_sdev = 0.001,
    eps_sdev = 0.0
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(laplace)) p$laplace <- laplace
  if (!missing(min_sdev)) p$min_sdev <- min_sdev
  if (!missing(eps_sdev)) p$eps_sdev <- eps_sdev
  .h2o.train_params("naivebayes", y, x, training_frame, validation_frame, p)
}

h2o.isolationForest <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    ntrees = 50,
    sample_size = 256,
    max_depth = 8,
    mtries = -1
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(sample_size)) p$sample_size <- sample_size
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(mtries)) p$mtries <- mtries
  .h2o.train_params("isolationforest", y, x, training_frame, validation_frame, p)
}

h2o.extendedIsolationForest <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    ntrees = 100,
    sample_size = 256,
    extension_level = -1
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(sample_size)) p$sample_size <- sample_size
  if (!missing(extension_level)) p$extension_level <- extension_level
  .h2o.train_params("extendedisolationforest", y, x, training_frame, validation_frame, p)
}

h2o.glrm <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    k = 2,
    loss = "Quadratic",
    regularization_x = "None",
    regularization_y = "None",
    gamma_x = 0.0,
    gamma_y = 0.0,
    max_iterations = 100,
    init_step_size = 1.0,
    min_step_size = 1e-06,
    tolerance_rel = 1e-07,
    transform = "STANDARDIZE",
    init = "SVD"
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(k)) p$k <- k
  if (!missing(loss)) p$loss <- loss
  if (!missing(regularization_x)) p$regularization_x <- regularization_x
  if (!missing(regularization_y)) p$regularization_y <- regularization_y
  if (!missing(gamma_x)) p$gamma_x <- gamma_x
  if (!missing(gamma_y)) p$gamma_y <- gamma_y
  if (!missing(max_iterations)) p$max_iterations <- max_iterations
  if (!missing(init_step_size)) p$init_step_size <- init_step_size
  if (!missing(min_step_size)) p$min_step_size <- min_step_size
  if (!missing(tolerance_rel)) p$tolerance_rel <- tolerance_rel
  if (!missing(transform)) p$transform <- transform
  if (!missing(init)) p$init <- init
  .h2o.train_params("glrm", y, x, training_frame, validation_frame, p)
}

h2o.coxph <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    start_column = NULL,
    stop_column = NULL,
    ties = "efron",
    max_iterations = 20,
    tolerance = 1e-08
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(start_column)) p$start_column <- start_column
  if (!missing(stop_column)) p$stop_column <- stop_column
  if (!missing(ties)) p$ties <- ties
  if (!missing(max_iterations)) p$max_iterations <- max_iterations
  if (!missing(tolerance)) p$tolerance <- tolerance
  .h2o.train_params("coxph", y, x, training_frame, validation_frame, p)
}

h2o.isotonicregression <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    out_of_bounds = "clip"
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(out_of_bounds)) p$out_of_bounds <- out_of_bounds
  .h2o.train_params("isotonicregression", y, x, training_frame, validation_frame, p)
}

h2o.adaBoost <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    ntrees = 50,
    max_depth = 1,
    min_rows = 10.0,
    nbins = 255,
    nbins_cats = 1024,
    nbins_top_level = 1024,
    min_split_improvement = 1e-05,
    sample_rate = 1.0,
    col_sample_rate_per_tree = 1.0,
    score_tree_interval = 5,
    grow_policy = "depthwise",
    max_leaves = 0,
    calibrate_model = FALSE,
    calibration_frame = NULL,
    calibration_method = "AUTO",
    nlearners = 50,
    weak_learner = "DT",
    learn_rate = 0.5
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(min_rows)) p$min_rows <- min_rows
  if (!missing(nbins)) p$nbins <- nbins
  if (!missing(nbins_cats)) p$nbins_cats <- nbins_cats
  if (!missing(nbins_top_level)) p$nbins_top_level <- nbins_top_level
  if (!missing(min_split_improvement)) p$min_split_improvement <- min_split_improvement
  if (!missing(sample_rate)) p$sample_rate <- sample_rate
  if (!missing(col_sample_rate_per_tree)) p$col_sample_rate_per_tree <- col_sample_rate_per_tree
  if (!missing(score_tree_interval)) p$score_tree_interval <- score_tree_interval
  if (!missing(grow_policy)) p$grow_policy <- grow_policy
  if (!missing(max_leaves)) p$max_leaves <- max_leaves
  if (!missing(calibrate_model)) p$calibrate_model <- calibrate_model
  if (!missing(calibration_frame)) p$calibration_frame <- calibration_frame
  if (!missing(calibration_method)) p$calibration_method <- calibration_method
  if (!missing(nlearners)) p$nlearners <- nlearners
  if (!missing(weak_learner)) p$weak_learner <- weak_learner
  if (!missing(learn_rate)) p$learn_rate <- learn_rate
  .h2o.train_params("adaboost", y, x, training_frame, validation_frame, p)
}

h2o.decision_tree <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    ntrees = 50,
    max_depth = 10,
    min_rows = 10.0,
    nbins = 255,
    nbins_cats = 1024,
    nbins_top_level = 1024,
    min_split_improvement = 1e-05,
    sample_rate = 1.0,
    col_sample_rate_per_tree = 1.0,
    score_tree_interval = 5,
    grow_policy = "depthwise",
    max_leaves = 0,
    calibrate_model = FALSE,
    calibration_frame = NULL,
    calibration_method = "AUTO"
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(min_rows)) p$min_rows <- min_rows
  if (!missing(nbins)) p$nbins <- nbins
  if (!missing(nbins_cats)) p$nbins_cats <- nbins_cats
  if (!missing(nbins_top_level)) p$nbins_top_level <- nbins_top_level
  if (!missing(min_split_improvement)) p$min_split_improvement <- min_split_improvement
  if (!missing(sample_rate)) p$sample_rate <- sample_rate
  if (!missing(col_sample_rate_per_tree)) p$col_sample_rate_per_tree <- col_sample_rate_per_tree
  if (!missing(score_tree_interval)) p$score_tree_interval <- score_tree_interval
  if (!missing(grow_policy)) p$grow_policy <- grow_policy
  if (!missing(max_leaves)) p$max_leaves <- max_leaves
  if (!missing(calibrate_model)) p$calibrate_model <- calibrate_model
  if (!missing(calibration_frame)) p$calibration_frame <- calibration_frame
  if (!missing(calibration_method)) p$calibration_method <- calibration_method
  .h2o.train_params("decisiontree", y, x, training_frame, validation_frame, p)
}

h2o.word2vec <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    vec_size = 100,
    window_size = 5,
    min_word_freq = 5,
    epochs = 5,
    learning_rate = 0.025,
    negative_samples = 5,
    sent_sample_rate = 0.001
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(vec_size)) p$vec_size <- vec_size
  if (!missing(window_size)) p$window_size <- window_size
  if (!missing(min_word_freq)) p$min_word_freq <- min_word_freq
  if (!missing(epochs)) p$epochs <- epochs
  if (!missing(learning_rate)) p$learning_rate <- learning_rate
  if (!missing(negative_samples)) p$negative_samples <- negative_samples
  if (!missing(sent_sample_rate)) p$sent_sample_rate <- sent_sample_rate
  .h2o.train_params("word2vec", y, x, training_frame, validation_frame, p)
}

h2o.stackedEnsemble <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    base_models = c(),
    metalearner_algorithm = "AUTO",
    metalearner_params = list(),
    metalearner_nfolds = 5
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(base_models)) p$base_models <- base_models
  if (!missing(metalearner_algorithm)) p$metalearner_algorithm <- metalearner_algorithm
  if (!missing(metalearner_params)) p$metalearner_params <- metalearner_params
  if (!missing(metalearner_nfolds)) p$metalearner_nfolds <- metalearner_nfolds
  .h2o.train_params("stackedensemble", y, x, training_frame, validation_frame, p)
}

h2o.targetencoder <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    holdout_type = "none",
    blending = FALSE,
    inflection_point = 10.0,
    smoothing = 20.0,
    noise = 0.0,
    fold_column = NULL,
    nfolds = 5,
    seed = -1,
    columns = c()
) {
  p <- list()
  if (!missing(holdout_type)) p$holdout_type <- holdout_type
  if (!missing(blending)) p$blending <- blending
  if (!missing(inflection_point)) p$inflection_point <- inflection_point
  if (!missing(smoothing)) p$smoothing <- smoothing
  if (!missing(noise)) p$noise <- noise
  if (!missing(fold_column)) p$fold_column <- fold_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(seed)) p$seed <- seed
  if (!missing(columns)) p$columns <- columns
  .h2o.train_params("targetencoder", y, x, training_frame, validation_frame, p)
}

h2o.rulefit <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    algorithm = "AUTO",
    min_rule_length = 3,
    max_rule_length = 3,
    max_num_rules = -1,
    model_type = "rules_and_linear",
    rule_generation_ntrees = 50,
    distribution = "AUTO",
    lambda = NULL,
    remove_duplicates = TRUE
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(algorithm)) p$algorithm <- algorithm
  if (!missing(min_rule_length)) p$min_rule_length <- min_rule_length
  if (!missing(max_rule_length)) p$max_rule_length <- max_rule_length
  if (!missing(max_num_rules)) p$max_num_rules <- max_num_rules
  if (!missing(model_type)) p$model_type <- model_type
  if (!missing(rule_generation_ntrees)) p$rule_generation_ntrees <- rule_generation_ntrees
  if (!missing(distribution)) p$distribution <- distribution
  if (!missing(lambda)) p$lambda <- lambda
  if (!missing(remove_duplicates)) p$remove_duplicates <- remove_duplicates
  .h2o.train_params("rulefit", y, x, training_frame, validation_frame, p)
}

h2o.upliftRandomForest <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    nbins_cats = 1024,
    treatment_column = "treatment",
    uplift_metric = "KL",
    ntrees = 50,
    max_depth = 10,
    min_rows = 10.0,
    mtries = -2,
    sample_rate = 0.632,
    nbins = 255,
    min_split_improvement = 1e-05,
    score_tree_interval = 10
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(nbins_cats)) p$nbins_cats <- nbins_cats
  if (!missing(treatment_column)) p$treatment_column <- treatment_column
  if (!missing(uplift_metric)) p$uplift_metric <- uplift_metric
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(min_rows)) p$min_rows <- min_rows
  if (!missing(mtries)) p$mtries <- mtries
  if (!missing(sample_rate)) p$sample_rate <- sample_rate
  if (!missing(nbins)) p$nbins <- nbins
  if (!missing(min_split_improvement)) p$min_split_improvement <- min_split_improvement
  if (!missing(score_tree_interval)) p$score_tree_interval <- score_tree_interval
  .h2o.train_params("upliftdrf", y, x, training_frame, validation_frame, p)
}

h2o.gam <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    family = "AUTO",
    gam_columns = c(),
    num_knots = c(),
    scale = c(),
    bs = c(),
    lambda = 0.0,
    standardize = TRUE,
    intercept = TRUE,
    max_iterations = 50,
    beta_epsilon = 1e-06,
    keep_gam_cols = FALSE
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(family)) p$family <- family
  if (!missing(gam_columns)) p$gam_columns <- gam_columns
  if (!missing(num_knots)) p$num_knots <- num_knots
  if (!missing(scale)) p$scale <- scale
  if (!missing(bs)) p$bs <- bs
  if (!missing(lambda)) p$lambda <- lambda
  if (!missing(standardize)) p$standardize <- standardize
  if (!missing(intercept)) p$intercept <- intercept
  if (!missing(max_iterations)) p$max_iterations <- max_iterations
  if (!missing(beta_epsilon)) p$beta_epsilon <- beta_epsilon
  if (!missing(keep_gam_cols)) p$keep_gam_cols <- keep_gam_cols
  .h2o.train_params("gam", y, x, training_frame, validation_frame, p)
}

h2o.modelSelection <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    mode = "maxr",
    family = "AUTO",
    max_predictor_number = 1,
    min_predictor_number = 1,
    intercept = TRUE,
    standardize = TRUE,
    p_values_threshold = 0.0,
    missing_values_handling = "mean_imputation"
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(mode)) p$mode <- mode
  if (!missing(family)) p$family <- family
  if (!missing(max_predictor_number)) p$max_predictor_number <- max_predictor_number
  if (!missing(min_predictor_number)) p$min_predictor_number <- min_predictor_number
  if (!missing(intercept)) p$intercept <- intercept
  if (!missing(standardize)) p$standardize <- standardize
  if (!missing(p_values_threshold)) p$p_values_threshold <- p_values_threshold
  if (!missing(missing_values_handling)) p$missing_values_handling <- missing_values_handling
  .h2o.train_params("modelselection", y, x, training_frame, validation_frame, p)
}

h2o.anovaglm <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    family = "AUTO",
    highest_interaction_term = 0,
    lambda = 0.0,
    standardize = TRUE
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(family)) p$family <- family
  if (!missing(highest_interaction_term)) p$highest_interaction_term <- highest_interaction_term
  if (!missing(lambda)) p$lambda <- lambda
  if (!missing(standardize)) p$standardize <- standardize
  .h2o.train_params("anovaglm", y, x, training_frame, validation_frame, p)
}

h2o.aggregator <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    target_num_exemplars = 5000,
    rel_tol_num_exemplars = 0.5,
    transform = "NORMALIZE",
    categorical_encoding = "AUTO"
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(target_num_exemplars)) p$target_num_exemplars <- target_num_exemplars
  if (!missing(rel_tol_num_exemplars)) p$rel_tol_num_exemplars <- rel_tol_num_exemplars
  if (!missing(transform)) p$transform <- transform
  if (!missing(categorical_encoding)) p$categorical_encoding <- categorical_encoding
  .h2o.train_params("aggregator", y, x, training_frame, validation_frame, p)
}

h2o.infogram <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    protected_columns = c(),
    safety_index_threshold = 0.1,
    relevance_index_threshold = 0.1,
    total_information_threshold = 0.1,
    net_information_threshold = 0.1,
    ntrees = 20,
    max_depth = 5,
    top_n_features = 50
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(protected_columns)) p$protected_columns <- protected_columns
  if (!missing(safety_index_threshold)) p$safety_index_threshold <- safety_index_threshold
  if (!missing(relevance_index_threshold)) p$relevance_index_threshold <- relevance_index_threshold
  if (!missing(total_information_threshold)) p$total_information_threshold <- total_information_threshold
  if (!missing(net_information_threshold)) p$net_information_threshold <- net_information_threshold
  if (!missing(ntrees)) p$ntrees <- ntrees
  if (!missing(max_depth)) p$max_depth <- max_depth
  if (!missing(top_n_features)) p$top_n_features <- top_n_features
  .h2o.train_params("infogram", y, x, training_frame, validation_frame, p)
}

h2o.psvm <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    kernel_type = "gaussian",
    gamma = -1.0,
    hyper_param = 1.0,
    positive_weight = 1.0,
    negative_weight = 1.0,
    rank_ratio = -1.0,
    max_iterations = 200,
    convergence_tol = 1e-06
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(kernel_type)) p$kernel_type <- kernel_type
  if (!missing(gamma)) p$gamma <- gamma
  if (!missing(hyper_param)) p$hyper_param <- hyper_param
  if (!missing(positive_weight)) p$positive_weight <- positive_weight
  if (!missing(negative_weight)) p$negative_weight <- negative_weight
  if (!missing(rank_ratio)) p$rank_ratio <- rank_ratio
  if (!missing(max_iterations)) p$max_iterations <- max_iterations
  if (!missing(convergence_tol)) p$convergence_tol <- convergence_tol
  .h2o.train_params("psvm", y, x, training_frame, validation_frame, p)
}

h2o.hglm <- function(
    y = NULL,
    x = NULL,
    training_frame,
    validation_frame = NULL,
    ignored_columns = c(),
    weights_column = NULL,
    offset_column = NULL,
    nfolds = 0,
    fold_assignment = "modulo",
    keep_cross_validation_predictions = FALSE,
    seed = -1,
    max_runtime_secs = 0.0,
    stopping_rounds = 0,
    stopping_metric = "AUTO",
    stopping_tolerance = 0.001,
    checkpoint = NULL,
    export_checkpoints_dir = NULL,
    random_columns = c(),
    method = "EM",
    max_iterations = 100,
    em_epsilon = 1e-06,
    standardize = FALSE,
    intercept = TRUE
) {
  p <- list()
  if (!missing(ignored_columns)) p$ignored_columns <- ignored_columns
  if (!missing(weights_column)) p$weights_column <- weights_column
  if (!missing(offset_column)) p$offset_column <- offset_column
  if (!missing(nfolds)) p$nfolds <- nfolds
  if (!missing(fold_assignment)) p$fold_assignment <- fold_assignment
  if (!missing(keep_cross_validation_predictions)) p$keep_cross_validation_predictions <- keep_cross_validation_predictions
  if (!missing(seed)) p$seed <- seed
  if (!missing(max_runtime_secs)) p$max_runtime_secs <- max_runtime_secs
  if (!missing(stopping_rounds)) p$stopping_rounds <- stopping_rounds
  if (!missing(stopping_metric)) p$stopping_metric <- stopping_metric
  if (!missing(stopping_tolerance)) p$stopping_tolerance <- stopping_tolerance
  if (!missing(checkpoint)) p$checkpoint <- checkpoint
  if (!missing(export_checkpoints_dir)) p$export_checkpoints_dir <- export_checkpoints_dir
  if (!missing(random_columns)) p$random_columns <- random_columns
  if (!missing(method)) p$method <- method
  if (!missing(max_iterations)) p$max_iterations <- max_iterations
  if (!missing(em_epsilon)) p$em_epsilon <- em_epsilon
  if (!missing(standardize)) p$standardize <- standardize
  if (!missing(intercept)) p$intercept <- intercept
  .h2o.train_params("hglm", y, x, training_frame, validation_frame, p)
}

