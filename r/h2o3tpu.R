# h2o3tpu — R client for the h2o3-tpu coordinator.
#
# Successor of the ``h2o-r`` package [UNVERIFIED upstream paths, SURVEY.md
# §2.3]: the same verb surface (h2o.init / h2o.importFile / h2o.gbm /
# h2o.predict / h2o.performance / h2o.automl / h2o.ls) speaking the same
# REST routes, in one dependency-light file. Transport is the system
# ``curl`` binary (no RCurl/httr); JSON via the ``jsonlite`` package.
#
# Usage:
#   source("h2o3tpu.R")
#   h2o.init("http://localhost:54321")
#   fr <- h2o.importFile("/data/train.csv")
#   m  <- h2o.gbm(y = "label", training_frame = fr, ntrees = 50)
#   h2o.performance(m)
#   p  <- h2o.predict(m, fr)

.h2o3 <- new.env(parent = emptyenv())

.h2o.json <- function(x) jsonlite::toJSON(x, auto_unbox = TRUE, null = "null")

.h2o.auth_args <- function() {
  # shared by every curl invocation (JSON requests AND the raw download /
  # upload / csv paths): a token-enabled server 401s any unauthenticated
  # route, and curl -o would silently write the error JSON into the file
  if (is.null(.h2o3$token)) character(0)
  else c("-H", paste0("Authorization: Bearer ", .h2o3$token))
}

.h2o.req <- function(method, path, body = NULL) {
  stopifnot(!is.null(.h2o3$url))
  url <- paste0(.h2o3$url, path)
  args <- c("-sS", "-X", method, url, .h2o.auth_args())
  if (!is.null(body)) {
    args <- c(args, "-H", "Content-Type: application/json",
              "--data-binary", as.character(.h2o.json(body)))
  }
  out <- suppressWarnings(system2("curl", shQuote(args), stdout = TRUE))
  txt <- paste(out, collapse = "\n")
  if (!nzchar(txt)) stop("empty response from ", url)
  res <- jsonlite::fromJSON(txt, simplifyVector = FALSE)
  if (!is.null(res$http_status) && res$http_status >= 400) {
    stop("H2O3 error ", res$http_status, ": ", res$msg)
  }
  res
}

.h2o.key <- function(x) {
  if (is.list(x) && !is.null(x$name)) x$name else x
}

.h2o.wait_job <- function(job, poll = 0.5) {
  key <- .h2o.key(job$key)
  repeat {
    j <- .h2o.req("GET", paste0("/3/Jobs/", key))
    jj <- if (!is.null(j$jobs)) j$jobs[[1]] else j
    if (jj$status %in% c("DONE", "FAILED", "CANCELLED")) {
      if (jj$status == "FAILED") stop("job ", key, " failed: ", jj$exception)
      return(invisible(jj))
    }
    Sys.sleep(poll)
  }
}

# -- connection ---------------------------------------------------------------

h2o.init <- function(url = "http://localhost:54321", token = NULL) {
  .h2o3$token <- if (is.null(token)) Sys.getenv("H2O3_TPU_AUTH_TOKEN", NA) else token
  if (is.na(.h2o3$token) || !nzchar(.h2o3$token)) .h2o3$token <- NULL
  .h2o3$url <- sub("/+$", "", url)
  cloud <- .h2o.req("GET", "/3/Cloud")
  message("Connected to ", cloud$cloud_name, " (", cloud$cloud_size,
          " device(s), version ", cloud$version, ")")
  invisible(cloud)
}

h2o.clusterInfo <- function() .h2o.req("GET", "/3/Cloud")

# -- frames -------------------------------------------------------------------

h2o.importFile <- function(path, destination_frame = NULL) {
  setup <- .h2o.req("POST", "/3/ParseSetup", list(source_frames = list(path)))
  body <- setup
  if (!is.null(destination_frame)) body$destination_frame <- destination_frame
  parsed <- .h2o.req("POST", "/3/Parse", body)
  .h2o.wait_job(parsed$job)
  structure(list(frame_id = .h2o.key(parsed$destination_frame)),
            class = "H2O3Frame")
}

h2o.getFrame <- function(id) {
  .h2o.req("GET", paste0("/3/Frames/", id))
}

h2o.ls <- function() {
  frames <- .h2o.req("GET", "/3/Frames")$frames
  models <- .h2o.req("GET", "/3/Models")$models
  keys <- c(vapply(frames, function(f) .h2o.key(f$frame_id), ""),
            vapply(models, function(m) .h2o.key(m$model_id), ""))
  data.frame(key = keys, stringsAsFactors = FALSE)
}

h2o.describe <- function(frame) {
  .h2o.req("GET", paste0("/3/Frames/", .h2o.key(frame$frame_id), "/summary"))
}

h2o.exportFile <- function(frame, path, force = FALSE) {
  .h2o.req("POST", paste0("/3/Frames/", .h2o.key(frame$frame_id), "/export"),
           list(path = path, force = force))
}

h2o.rm <- function(key) {
  key <- if (inherits(key, "H2O3Frame")) .h2o.key(key$frame_id) else key
  invisible(.h2o.req("DELETE", paste0("/3/Frames/", key)))
}

# -- model builders -----------------------------------------------------------

.h2o.train <- function(algo, y = NULL, x = NULL, training_frame,
                       validation_frame = NULL, ...) {
  body <- list(training_frame = .h2o.key(training_frame$frame_id), ...)
  if (!is.null(y)) body$response_column <- y
  if (!is.null(x)) body$x <- as.list(x)
  if (!is.null(validation_frame)) {
    body$validation_frame <- .h2o.key(validation_frame$frame_id)
  }
  res <- .h2o.req("POST", paste0("/3/ModelBuilders/", algo), body)
  jj <- .h2o.wait_job(res$job)
  mid <- .h2o.key(jj$dest)  # /3/Jobs reports the model key once DONE
  if (is.null(mid) || !nzchar(mid)) {
    models <- .h2o.req("GET", "/3/Models")$models
    mid <- .h2o.key(models[[length(models)]]$model_id)
  }
  structure(list(model_id = mid, algo = algo), class = "H2O3Model")
}

h2o.gbm <- function(...) .h2o.train("gbm", ...)
h2o.xgboost <- function(...) .h2o.train("xgboost", ...)
h2o.randomForest <- function(...) .h2o.train("drf", ...)
h2o.glm <- function(...) .h2o.train("glm", ...)
h2o.deeplearning <- function(...) .h2o.train("deeplearning", ...)
h2o.kmeans <- function(...) .h2o.train("kmeans", ...)
h2o.prcomp <- function(...) .h2o.train("pca", ...)
h2o.naiveBayes <- function(...) .h2o.train("naivebayes", ...)
h2o.isolationForest <- function(...) .h2o.train("isolationforest", ...)
h2o.gam <- function(...) .h2o.train("gam", ...)
h2o.rulefit <- function(...) .h2o.train("rulefit", ...)
h2o.upliftRandomForest <- function(...) .h2o.train("upliftdrf", ...)
h2o.coxph <- function(...) .h2o.train("coxph", ...)
h2o.psvm <- function(...) .h2o.train("psvm", ...)
h2o.modelSelection <- function(...) .h2o.train("modelselection", ...)
h2o.anovaglm <- function(...) .h2o.train("anovaglm", ...)
h2o.aggregator <- function(...) .h2o.train("aggregator", ...)
h2o.infogram <- function(...) .h2o.train("infogram", ...)
h2o.targetencoder <- function(...) .h2o.train("targetencoder", ...)
h2o.isotonicregression <- function(...) .h2o.train("isotonicregression", ...)
h2o.svd <- function(...) .h2o.train("svd", ...)
h2o.glrm <- function(...) .h2o.train("glrm", ...)
h2o.extendedIsolationForest <- function(...) .h2o.train("extendedisolationforest", ...)
h2o.decision_tree <- function(...) .h2o.train("decisiontree", ...)
h2o.adaBoost <- function(...) .h2o.train("adaboost", ...)
h2o.word2vec <- function(...) .h2o.train("word2vec", ...)
h2o.stackedEnsemble <- function(...) .h2o.train("stackedensemble", ...)
h2o.hglm <- function(...) .h2o.train("hglm", ...)
h2o.xrt <- function(...) .h2o.train("xrt", ...)

# -- scoring / inspection -----------------------------------------------------

h2o.getModel <- function(id) {
  res <- .h2o.req("GET", paste0("/3/Models/", id))
  res$models[[1]]
}

h2o.predict <- function(model, frame) .h2o.predictions(model, frame)

h2o.performance <- function(model, frame = NULL) {
  m <- h2o.getModel(model$model_id)
  if (is.null(frame)) return(m$output$training_metrics)
  res <- .h2o.req("POST", paste0("/3/ModelMetrics/models/", model$model_id,
                                 "/frames/", .h2o.key(frame$frame_id)), list())
  res$model_metrics
}

h2o.varimp <- function(model) h2o.getModel(model$model_id)$output$variable_importances

h2o.auc <- function(perf) perf$auc
h2o.rmse <- function(perf) perf$rmse
h2o.logloss <- function(perf) perf$logloss

h2o.download_mojo <- function(model, path = ".") {
  url <- paste0(.h2o3$url, "/3/Models/", model$model_id, "/mojo")
  dest <- file.path(path, paste0(model$model_id, ".zip"))
  system2("curl", shQuote(c("-sS", .h2o.auth_args(), "-o", dest, url)))
  dest
}

# -- grids + automl -----------------------------------------------------------

h2o.grid <- function(algo, hyper_params, training_frame, y = NULL, x = NULL,
                     search_criteria = NULL, parallelism = 1, ...) {
  body <- list(hyper_parameters = hyper_params,
               training_frame = .h2o.key(training_frame$frame_id),
               parallelism = parallelism, ...)
  if (!is.null(y)) body$response_column <- y
  if (!is.null(x)) body$x <- as.list(x)
  if (!is.null(search_criteria)) body$search_criteria <- search_criteria
  res <- .h2o.req("POST", paste0("/99/Grid/", algo), body)
  .h2o.wait_job(res$job)
  .h2o.req("GET", paste0("/99/Grids/", .h2o.key(res$grid_id)))
}

h2o.automl <- function(y, training_frame, max_models = 10, nfolds = NULL, ...) {
  build_control <- list(stopping_criteria = list(max_models = max_models))
  if (!is.null(nfolds)) build_control$nfolds <- nfolds
  body <- list(
    build_control = build_control,
    input_spec = list(
      training_frame = list(name = .h2o.key(training_frame$frame_id)),
      response_column = list(column_name = y)),
    build_models = list(...))
  res <- .h2o.req("POST", "/99/AutoMLBuilder", body)
  if (!is.null(res$job)) .h2o.wait_job(res$job)
  .h2o.req("GET", paste0("/99/AutoML/", .h2o.key(res$automl_id)))
}

# -- rapids (frame expressions) ----------------------------------------------

h2o.rapids <- function(ast) .h2o.req("POST", "/99/Rapids", list(ast = ast))

# run an AST, bind the result to a fresh key, return a frame handle
.h2o.rapids_frame <- function(ast) {
  # never touch the user's global RNG stream (set.seed reproducibility)
  .h2o3$tmpctr <- if (is.null(.h2o3$tmpctr)) 1L else .h2o3$tmpctr + 1L
  key <- sprintf("rtmp_%d_%s", .h2o3$tmpctr,
                 gsub("[^0-9]", "", format(Sys.time(), "%H%M%OS3")))
  .h2o.req("POST", "/99/Rapids", list(ast = sprintf("(tmp= %s %s)", key, ast)))
  structure(list(frame_id = key), class = "H2O3Frame")
}

.h2o.fref <- function(fr) .h2o.key(fr$frame_id)

.h2o.rvec <- function(x) {
  if (is.character(x)) paste0("[", paste(sprintf("'%s'", x), collapse = " "), "]")
  else paste0("[", paste(x, collapse = " "), "]")
}

# -- frame manipulation (ASTMerge/Sort/Group/... successors over Rapids) -----

h2o.merge <- function(x, y, all.x = FALSE, all.y = FALSE) {
  .h2o.rapids_frame(sprintf("(merge %s %s %s %s)", .h2o.fref(x), .h2o.fref(y),
                            if (all.x) "TRUE" else "FALSE",
                            if (all.y) "TRUE" else "FALSE"))
}

h2o.arrange <- function(fr, by, ascending = TRUE) {
  asc <- as.integer(rep(ascending, length.out = length(by)))
  .h2o.rapids_frame(sprintf("(sort %s %s %s)", .h2o.fref(fr), .h2o.rvec(by),
                            .h2o.rvec(asc)))
}

h2o.unique <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(unique (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.table <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(table (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.quantile <- function(fr, probs = c(0.25, 0.5, 0.75)) {
  .h2o.rapids_frame(sprintf("(quantile %s %s)", .h2o.fref(fr), .h2o.rvec(probs)))
}

h2o.match <- function(fr, col, table, nomatch = NaN) {
  .h2o.rapids_frame(sprintf("(match (cols %s '%s') %s %s 1)", .h2o.fref(fr),
                            col, .h2o.rvec(table),
                            if (is.nan(nomatch)) "NaN" else nomatch))
}

h2o.which <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(which (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.na_omit <- function(fr) {
  .h2o.rapids_frame(sprintf("(na.omit %s)", .h2o.fref(fr)))
}

h2o.rank_within_group_by <- function(fr, group_by_cols, sort_cols,
                                     ascending = TRUE,
                                     new_col_name = "New_Rank_column",
                                     sort_cols_sorted = FALSE) {
  .h2o.rapids_frame(sprintf(
    "(rank_within_groupby %s %s %s %s '%s' %s)", .h2o.fref(fr),
    .h2o.rvec(group_by_cols), .h2o.rvec(sort_cols),
    .h2o.rvec(as.integer(rep(ascending, length.out = length(sort_cols)))),
    new_col_name, if (sort_cols_sorted) "TRUE" else "FALSE"))
}

h2o.pivot <- function(fr, index, column, value) {
  .h2o.rapids_frame(sprintf("(pivot %s '%s' '%s' '%s')", .h2o.fref(fr),
                            index, column, value))
}

h2o.stratified_split <- function(fr, col, test_frac = 0.2, seed = -1) {
  .h2o.rapids_frame(sprintf("(h2o.random_stratified_split (cols %s '%s') %s %s)",
                            .h2o.fref(fr), col, test_frac, seed))
}

h2o.impute <- function(fr, column, method = "mean") {
  .h2o.req("POST", "/99/Rapids", list(ast = sprintf(
    "(h2o.impute %s '%s' '%s')", .h2o.fref(fr), column, method)))
}

h2o.group_by <- function(fr, by, ...) {
  # aggregations as named args: h2o.group_by(fr, "g", mean = "x", nrow = "x")
  aggs <- list(...)
  spec <- paste(vapply(seq_along(aggs), function(i) {
    sprintf("%s '%s' 'all'", names(aggs)[i], aggs[[i]])
  }, ""), collapse = " ")
  .h2o.rapids_frame(sprintf("(GB %s %s %s)", .h2o.fref(fr), .h2o.rvec(by), spec))
}

h2o.cbind <- function(...) {
  frs <- list(...)
  .h2o.rapids_frame(sprintf("(cbind %s)",
                            paste(vapply(frs, .h2o.fref, ""), collapse = " ")))
}

h2o.rbind <- function(...) {
  frs <- list(...)
  .h2o.rapids_frame(sprintf("(rbind %s)",
                            paste(vapply(frs, .h2o.fref, ""), collapse = " ")))
}

.h2o.lit <- function(x) {
  # scalar literal for an AST: strings must be quoted or the evaluator
  # resolves them as DKV identifiers
  if (is.character(x)) sprintf("'%s'", x)
  else if (is.logical(x)) (if (x) "TRUE" else "FALSE")
  else as.character(x)
}

h2o.ifelse <- function(fr, col, yes, no) {
  .h2o.rapids_frame(sprintf("(ifelse (cols %s '%s') %s %s)", .h2o.fref(fr),
                            col, .h2o.lit(yes), .h2o.lit(no)))
}

h2o.cut <- function(fr, col, breaks, labels = NULL,
                    include.lowest = FALSE, right = TRUE) {
  lab <- if (is.null(labels)) "null" else .h2o.rvec(labels)
  .h2o.rapids_frame(sprintf("(cut (cols %s '%s') %s %s %s %s)", .h2o.fref(fr),
                            col, .h2o.rvec(breaks), lab,
                            if (include.lowest) "TRUE" else "FALSE",
                            if (right) "TRUE" else "FALSE"))
}

h2o.scale <- function(fr, center = TRUE, scale = TRUE) {
  .h2o.rapids_frame(sprintf("(scale %s %s %s)", .h2o.fref(fr),
                            if (center) "TRUE" else "FALSE",
                            if (scale) "TRUE" else "FALSE"))
}

h2o.cor <- function(fr) {
  .h2o.rapids_frame(sprintf("(cor %s)", .h2o.fref(fr)))
}

h2o.hist <- function(fr, col, breaks = 20) {
  # the server takes a bin COUNT (break vectors are not supported on the
  # wire); a vector here would also vectorize sprintf into a malformed AST
  stopifnot(is.numeric(breaks), length(breaks) == 1)
  .h2o.rapids_frame(sprintf("(hist (cols %s '%s') %s)", .h2o.fref(fr), col, breaks))
}

h2o.levels <- function(fr, col) {
  # from frame metadata (a structured JSON list), NOT the rapids string
  # repr — levels containing commas or quotes must round-trip exactly
  meta <- h2o.getFrame(.h2o.fref(fr))
  for (c in meta$columns) {
    if (identical(c$label, col)) return(unlist(c$domain))
  }
  stop("no column '", col, "' in frame")
}

h2o.nlevels <- function(fr, col) length(h2o.levels(fr, col))

h2o.asfactor <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(as.factor (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.asnumeric <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(as.numeric (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.round <- function(fr, col, digits = 0) {
  .h2o.rapids_frame(sprintf("(round (cols %s '%s') %s)", .h2o.fref(fr), col, digits))
}

h2o.signif <- function(fr, col, digits = 6) {
  .h2o.rapids_frame(sprintf("(signif (cols %s '%s') %s)", .h2o.fref(fr), col, digits))
}

h2o.toupper <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(toupper (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.tolower <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(tolower (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.trim <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(trim (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.nchar <- function(fr, col) {
  .h2o.rapids_frame(sprintf("(nchar (cols %s '%s'))", .h2o.fref(fr), col))
}

h2o.gsub <- function(pattern, replacement, fr, col) {
  .h2o.rapids_frame(sprintf("(gsub '%s' '%s' (cols %s '%s'))", pattern,
                            replacement, .h2o.fref(fr), col))
}

h2o.sub <- function(pattern, replacement, fr, col) {
  .h2o.rapids_frame(sprintf("(sub '%s' '%s' (cols %s '%s'))", pattern,
                            replacement, .h2o.fref(fr), col))
}

h2o.substring <- function(fr, col, first, last = NULL) {
  # R convention is 1-based INCLUSIVE first..last; the wire takes a 0-based
  # exclusive-end slice, so ship (first-1, last) like upstream's client
  .h2o.rapids_frame(sprintf("(substring (cols %s '%s') %s%s)", .h2o.fref(fr),
                            col, first - 1,
                            if (is.null(last)) "" else paste0(" ", last)))
}

h2o.year <- function(fr, col) .h2o.time_part("year", fr, col)
h2o.month <- function(fr, col) .h2o.time_part("month", fr, col)
h2o.day <- function(fr, col) .h2o.time_part("day", fr, col)
h2o.hour <- function(fr, col) .h2o.time_part("hour", fr, col)
h2o.dayOfWeek <- function(fr, col) .h2o.time_part("dayOfWeek", fr, col)
h2o.week <- function(fr, col) .h2o.time_part("week", fr, col)

.h2o.time_part <- function(part, fr, col) {
  .h2o.rapids_frame(sprintf("(%s (cols %s '%s'))", part, .h2o.fref(fr), col))
}

h2o.mean <- function(fr, col) .h2o.col_reduce("mean", fr, col)
h2o.sum <- function(fr, col) .h2o.col_reduce("sum", fr, col)
h2o.sd <- function(fr, col) .h2o.col_reduce("sd", fr, col)
h2o.var <- function(fr, col) .h2o.col_reduce("var", fr, col)
h2o.median <- function(fr, col) .h2o.col_reduce("median", fr, col)

.h2o.col_reduce <- function(agg, fr, col) {
  out <- h2o.rapids(sprintf("(%s (cols %s '%s'))", agg, .h2o.fref(fr), col))
  as.numeric(out$scalar)
}

# -- frame download / description --------------------------------------------

as.data.frame.H2O3Frame <- function(x, ...) {
  url <- paste0(.h2o3$url, "/3/DownloadDataset?frame_id=",
                utils::URLencode(.h2o.fref(x), TRUE))
  tmp <- tempfile(fileext = ".csv")
  system2("curl", shQuote(c("-sS", .h2o.auth_args(), "-o", tmp, url)))
  utils::read.csv(tmp)
}

h2o.uploadFile <- function(path, destination_frame = NULL) {
  url <- paste0(.h2o3$url, "/3/PostFile?filename=",
                utils::URLencode(basename(path), TRUE))
  if (!is.null(destination_frame)) {
    url <- paste0(url, "&destination_frame=",
                  utils::URLencode(destination_frame, TRUE))
  }
  res <- system2("curl", shQuote(c("-sS", .h2o.auth_args(), "-X", "POST", "--data-binary",
                                   paste0("@", path), url)), stdout = TRUE)
  parsed <- jsonlite::fromJSON(paste(res, collapse = ""))
  # PostFile already parses server-side and returns the new frame's KEY
  structure(list(frame_id = .h2o.key(parsed$destination_frame)),
            class = "H2O3Frame")
}

# -- model persistence --------------------------------------------------------

h2o.saveModel <- function(model, path = ".") {
  res <- .h2o.req("POST", paste0("/99/Models.bin/", model$model_id,
                                 "?dir=", utils::URLencode(path, TRUE)))
  res$dir
}

h2o.loadModel <- function(path) {
  res <- .h2o.req("POST", paste0("/99/Models.bin?dir=",
                                 utils::URLencode(path, TRUE)))
  m <- res$models[[1]]
  structure(list(model_id = .h2o.key(m$model_id), algo = m$algo),
            class = "H2O3Model")
}

h2o.confusionMatrix <- function(perf) perf$confusion_matrix
h2o.scoreHistory <- function(model) h2o.getModel(model$model_id)$output$scoring_history
h2o.shutdown <- function() invisible(NULL)  # coordinator lifecycle is external

h2o.make_metrics <- function(predicted, actuals, domain = NULL,
                             distribution = "gaussian") {
  body <- list(distribution = distribution)
  if (!is.null(domain)) body$domain <- as.list(domain)
  res <- .h2o.req("POST", paste0("/3/ModelMetrics/predictions_frame/",
                                 .h2o.fref(predicted), "/actuals_frame/",
                                 .h2o.fref(actuals)), body)
  res$model_metrics[[1]]
}

h2o.partialPlot <- function(model, frame, cols, nbins = 20) {
  res <- .h2o.req("POST", "/3/PartialDependence", list(
    model_id = model$model_id, frame_id = .h2o.fref(frame),
    cols = as.list(cols), nbins = nbins))
  res$partial_dependence_data
}

h2o.interaction <- function(frame, factors, pairwise = FALSE,
                            max_factors = 100, min_occurrence = 1,
                            destination_frame = NULL) {
  body <- list(source_frame = .h2o.fref(frame), factor_columns = as.list(factors),
               pairwise = pairwise, max_factors = max_factors,
               min_occurrence = min_occurrence)
  if (!is.null(destination_frame)) body$dest <- destination_frame
  res <- .h2o.req("POST", "/3/Interaction", body)
  structure(list(frame_id = .h2o.key(res$destination_frame)),
            class = "H2O3Frame")
}

h2o.splitFrame <- function(frame, ratios = 0.75, destination_frames = NULL,
                           seed = 1234) {
  body <- list(dataset = .h2o.fref(frame), ratios = as.list(ratios),
               seed = seed)
  if (!is.null(destination_frames)) {
    body$destination_frames <- as.list(destination_frames)
  }
  res <- .h2o.req("POST", "/3/SplitFrame", body)
  lapply(res$destination_frames, function(d) {
    structure(list(frame_id = .h2o.key(d)), class = "H2O3Frame")
  })
}

h2o.createFrame <- function(rows = 10000, cols = 10, seed = -1,
                            categorical_fraction = 0.2,
                            integer_fraction = 0.2, binary_fraction = 0.1,
                            missing_fraction = 0.0, factors = 100,
                            has_response = FALSE, response_factors = 2) {
  res <- .h2o.req("POST", "/3/CreateFrame", list(
    rows = rows, cols = cols, seed = seed,
    categorical_fraction = categorical_fraction,
    integer_fraction = integer_fraction, binary_fraction = binary_fraction,
    missing_fraction = missing_fraction, factors = factors,
    has_response = has_response, response_factors = response_factors))
  structure(list(frame_id = .h2o.key(res$destination_frame)),
            class = "H2O3Frame")
}

# -- generated explicit-argument estimators -----------------------------------
# estimators_gen.R (tools/gen_bindings.py output) defines h2o.gbm/h2o.glm/...
# with every parameter as a named argument; when present next to this file it
# shadows the minimal `...` wrappers above. Sourcing it is optional — both
# surfaces speak the same /3/ModelBuilders routes.
local({
  f <- tryCatch(sys.frame(1)$ofile, error = function(e) NULL)
  gen <- if (!is.null(f) && nzchar(f)) {
    file.path(dirname(f), "estimators_gen.R")
  } else {
    "estimators_gen.R"
  }
  if (file.exists(gen)) source(gen)
})

.h2o.predictions <- function(model, frame, options = list()) {
  res <- .h2o.req("POST", paste0("/3/Predictions/models/", model$model_id,
                                 "/frames/", .h2o.key(frame$frame_id)),
                  options)
  structure(list(frame_id = .h2o.key(res$predictions_frame)),
            class = "H2O3Frame")
}

h2o.predict_contributions <- function(model, frame) {
  .h2o.predictions(model, frame, list(predict_contributions = TRUE))
}

h2o.predict_leaf_node_assignment <- function(model, frame, type = "Path") {
  .h2o.predictions(model, frame, list(leaf_node_assignment = TRUE,
                                      leaf_node_assignment_type = type))
}

h2o.anomaly <- function(model, frame) {
  .h2o.predictions(model, frame, list(reconstruction_error = TRUE))
}
